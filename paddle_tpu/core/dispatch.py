"""Eager op dispatch + grad recording.

This is the TPU-native replacement for the reference dygraph tracer
(/root/reference/paddle/fluid/imperative/tracer.cc:186 TraceOpImpl and the
eager engine /root/reference/paddle/fluid/eager/): every framework op is a
functional JAX computation; when gradients are required we obtain the op's
VJP closure via jax.vjp at call time (one forward execution, residuals live
on device) and record a GradNode on the tape.  There is exactly ONE autograd
engine — no legacy/eager split.

Inside `paddle_tpu.jit.to_static` traces the tape is bypassed entirely:
differentiation of compiled programs happens through jax.grad on the
functionalized program, which is the idiomatic XLA path.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, List

import jax
import jax.numpy as jnp

from . import tape as tape_mod
from .flags import flag


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.in_static_trace = False


_state = _State()

# Static-graph recorder hook: paddle_tpu.static.graph installs a callback
# while static mode is enabled; apply() routes ops that touch symbolic
# Variables to it (the reference's dygraph/static mode switch,
# /root/reference/python/paddle/fluid/framework.py in_dygraph_mode).
NOT_RECORDED = object()  # recorder return value meaning "run eagerly"
_graph_recorder = None


def set_graph_recorder(recorder):
    global _graph_recorder
    prev = _graph_recorder
    _graph_recorder = recorder
    return prev


def is_grad_enabled() -> bool:
    # NB: the tape keeps recording inside to_static traces — jax.vjp over
    # tracers is what lets loss.backward() + optimizer.step() compile into
    # the one traced program.  in_static_trace only gates data-dependent-shape
    # ops (nonzero/unique/...), which must raise under a trace.
    return _state.grad_enabled


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


@contextlib.contextmanager
def no_grad_ctx():
    prev = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def enable_grad_ctx():
    prev = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def static_trace_guard():
    """Active while jit.to_static traces user code: tape off, ops trace into XLA."""
    prev = _state.in_static_trace
    _state.in_static_trace = True
    try:
        yield
    finally:
        _state.in_static_trace = prev


def in_static_trace() -> bool:
    return _state.in_static_trace


class no_grad:
    """Context manager AND decorator, like paddle.no_grad."""

    def __enter__(self):
        self._prev = _state.grad_enabled
        _state.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad_ctx():
                return fn(*args, **kwargs)

        return wrapper


_TENSOR_CLS = None  # lazy-cached: a per-op-call import is hot-path cost


def _tensor_cls():
    global _TENSOR_CLS
    if _TENSOR_CLS is None:
        from .tensor import Tensor

        _TENSOR_CLS = Tensor
    return _TENSOR_CLS


def _is_tensor(x):
    return isinstance(x, _tensor_cls())


_AMP_FN = None


def _amp_op_dtype_fn():
    """Cached ref to amp.amp_op_dtype (None until the amp module imports —
    a try/import per op call is hot-path cost)."""
    global _AMP_FN
    if _AMP_FN is None:
        try:
            from ..amp import amp_op_dtype

            _AMP_FN = amp_op_dtype
        except ImportError:  # during early package import
            return None
    return _AMP_FN


# dtypes are interned; cache differentiability per dtype instead of calling
# jnp.issubdtype/result_type on every op argument (eager hot path)
_DIFF_DTYPE_CACHE = {}


# ---------------------------------------------------------------------------
# Analytic eager VJP rules: jax.vjp re-linearizes the op on EVERY eager call
# (measured ~3050 us/op on this image's CPU for a 6-op fwd+bwd training
# chain vs ~250 us/op with the rules — 11.9x; gated by
# tools/check_eager_overhead.py), which is pure overhead when the backward
# is a closed form.  We record the closed form directly and skip jax.vjp —
# the analog of the reference's codegen'd per-op GradNode pairs
# (imperative/tracer.cc TraceOpImpl + generated grad ops).  jax.vjp remains
# the fallback for everything else (and for double-grad, which re-derives
# through dispatch).  A rule fires only when `fn` IS the registered callable
# and the rule accepts the call's attrs — a same-named op with a different
# closure or unsupported attr combination falls back.  The hot-set rules
# (matmul/linear/reductions/activations/layer_norm/embedding/reshape/
# transpose) register from their op modules via register_eager_vjp.
def _unbroadcast(ct, shape, dtype):
    shape = tuple(shape)
    if ct.shape != shape:
        extra = ct.ndim - len(shape)
        if extra > 0:
            ct = ct.sum(axis=tuple(range(extra)))
        axes = tuple(i for i, s in enumerate(shape)
                     if s == 1 and ct.shape[i] != 1)
        if axes:
            ct = ct.sum(axis=axes, keepdims=True)
    if ct.dtype != dtype:
        ct = ct.astype(dtype)
    return ct


# name -> tuple of (impl_fn, rule).  rule(vals, attrs) returns
# (out, vjp_over_all_inputs) or None to fall back to jax.vjp for this
# particular call (unsupported attr combination, odd ranks, ...).
_EAGER_VJP_RULES = {}


def register_eager_vjp(name, impl_fn, rule, allow_containers=False):
    """Register a closed-form eager VJP for op `name` when dispatched with
    `impl_fn` (matched by identity — a same-named op arriving with a
    different closure falls back to jax.vjp).  Multiple impls may share a
    name (e.g. linear with/without bias).  With allow_containers the rule
    also fires for container-arg ops (concat/stack): it then receives the
    FLATTENED tensor leaves in pytree order."""
    _EAGER_VJP_RULES[name] = _EAGER_VJP_RULES.get(name, ()) + (
        (impl_fn, rule, allow_containers),)


def eager_binop_rule(fwd, bwd):
    def rule(vals, attrs):
        if attrs:
            return None
        a, b = vals
        out = fwd(a, b)

        def vjp(ct):
            ga, gb = bwd(ct, a, b, out)
            return (_unbroadcast(ga, a.shape, a.dtype),
                    _unbroadcast(gb, b.shape, b.dtype))
        return out, vjp
    return rule


def eager_unop_rule(fwd, bwd):
    def rule(vals, attrs):
        if attrs:
            return None
        (a,) = vals
        out = fwd(a)
        return out, lambda ct: (bwd(ct, a, out).astype(a.dtype),)
    return rule


def _silu_bwd(ct, a, o):
    # d/dx x*s(x) = s + x*s*(1-s) = s + o*(1-s)
    s = jax.nn.sigmoid(a)
    return ct * (s + o * (1.0 - s))


def _register_builtin_rules():
    unop, binop = eager_unop_rule, eager_binop_rule
    for name, impl, rule in (
        ("add", jnp.add, binop(jnp.add, lambda ct, a, b, o: (ct, ct))),
        ("subtract", jnp.subtract, binop(
            jnp.subtract, lambda ct, a, b, o: (ct, -ct))),
        ("multiply", jnp.multiply, binop(
            jnp.multiply, lambda ct, a, b, o: (ct * b, ct * a))),
        ("divide", jnp.divide, binop(
            jnp.divide, lambda ct, a, b, o: (ct / b, -ct * o / b))),
        ("exp", jnp.exp, unop(jnp.exp, lambda ct, a, o: ct * o)),
        ("log", jnp.log, unop(jnp.log, lambda ct, a, o: ct / a)),
        ("tanh", jnp.tanh, unop(
            jnp.tanh, lambda ct, a, o: ct * (1.0 - o * o))),
        ("sqrt", jnp.sqrt, unop(
            jnp.sqrt, lambda ct, a, o: ct * 0.5 / o)),
        ("rsqrt", jax.lax.rsqrt, unop(
            jax.lax.rsqrt, lambda ct, a, o: ct * -0.5 * o * o * o)),
        # activations dispatched with their jax.nn callable directly
        ("relu", jax.nn.relu, unop(
            jax.nn.relu, lambda ct, a, o: jnp.where(a > 0, ct, 0))),
        ("sigmoid", jax.nn.sigmoid, unop(
            jax.nn.sigmoid, lambda ct, a, o: ct * o * (1.0 - o))),
        ("silu", jax.nn.silu, unop(jax.nn.silu, _silu_bwd)),
        ("swish", jax.nn.silu, unop(jax.nn.silu, _silu_bwd)),
    ):
        register_eager_vjp(name, impl, rule)


_register_builtin_rules()


def _differentiable_dtype(v) -> bool:
    dt = getattr(v, "dtype", None)
    if dt is None:
        return jnp.issubdtype(jnp.result_type(v), jnp.inexact)
    hit = _DIFF_DTYPE_CACHE.get(dt)
    if hit is None:
        hit = _DIFF_DTYPE_CACHE[dt] = bool(
            jnp.issubdtype(dt, jnp.inexact))
    return hit


def apply(name: str, fn, *args, _differentiable: bool = True, **attrs):
    """Run op `fn` over args (Tensors possibly nested in lists/tuples) with
    static keyword attrs; wrap outputs in Tensors and record the grad node.
    """
    Tensor = _tensor_cls()

    if _graph_recorder is not None:
        rec = _graph_recorder(name, fn, args, attrs)
        if rec is not NOT_RECORDED:
            return rec

    # fast path: args with no containers skip the pytree machinery (the
    # overwhelmingly common case — reference hot loop analog TraceOpImpl).
    # ONE fused scan builds flat/tensor_idx/diff_idx: this wrapper is the
    # per-op eager hot loop (reference TraceOpImpl + PrepareImpl), and
    # the previous four generator passes over the args were ~40% of the
    # measured dispatch overhead.
    for a in args:
        if isinstance(a, (list, tuple, dict)):
            flat, treedef = jax.tree_util.tree_flatten(
                args, is_leaf=_is_tensor)
            break
    else:
        flat, treedef = list(args), None

    grad_on = _differentiable and _state.grad_enabled
    tensor_idx = []
    diff_idx = []
    for i, leaf in enumerate(flat):
        if isinstance(leaf, Tensor):
            tensor_idx.append(i)
            # differentiable leaves become vjp arguments, the rest are
            # closed over as constants
            if grad_on and not leaf.stop_gradient and \
                    _differentiable_dtype(leaf._value):
                diff_idx.append(i)
    record = bool(diff_idx)

    # AMP O1/O2: per-op cast decision (reference: imperative/tracer.cc:224
    # AutoCastInputs / amp_auto_cast.cc).  The cast happens inside raw_fn so
    # the vjp closure differentiates through it.
    amp_np_dtype = None
    amp_fn = _amp_op_dtype_fn()
    if amp_fn is not None:
        amp_target = amp_fn(name)
        if amp_target is not None:
            from .dtype import to_np

            amp_np_dtype = to_np(amp_target)

    def _amp_cast(v):
        if amp_np_dtype is not None and jnp.issubdtype(
                jnp.result_type(v), jnp.floating):
            return v.astype(amp_np_dtype)
        return v

    def raw_fn(*diff_vals):
        new_flat = list(flat)
        for pos, v in zip(diff_idx, diff_vals):
            new_flat[pos] = _amp_cast(v)
        for i in tensor_idx:
            if i not in diff_idx:
                new_flat[i] = _amp_cast(new_flat[i]._value)
        if treedef is None:
            return fn(*new_flat, **attrs)
        new_args = jax.tree_util.tree_unflatten(treedef, new_flat)
        return fn(*new_args, **attrs)

    if record:
        out_raw = None
        rule_entries = _EAGER_VJP_RULES.get(name)
        if (rule_entries is not None and amp_np_dtype is None
                and len(tensor_idx) == len(flat)):
            for impl_fn, rule, allow_containers in rule_entries:
                if impl_fn is fn and (treedef is None
                                      or allow_containers):
                    res = rule([t._value for t in flat], attrs)
                    if res is not None:
                        out_raw, vjp_all = res
                    break
        if out_raw is not None:
            if len(diff_idx) == len(flat):
                vjp_fn = vjp_all
            else:
                sel = tuple(diff_idx)

                def vjp_fn(ct, _v=vjp_all, _sel=sel):
                    gs = _v(ct)
                    return tuple(gs[i] for i in _sel)
        if out_raw is None:
            diff_vals = [flat[i]._value for i in diff_idx]
            out_raw, vjp_fn = jax.vjp(raw_fn, *diff_vals)
        node = tape_mod.GradNode(name, vjp_fn)
        node.grad_raw_fn = raw_fn  # double-grad: recordable vjp recompute
    else:
        out_raw = raw_fn()
        node = None

    single = not isinstance(out_raw, (tuple, list))
    out_list = [out_raw] if single else list(out_raw)

    outputs: List[Any] = []
    for i, o in enumerate(out_list):
        diff_out = record and _differentiable_dtype(o)
        t = Tensor(o, stop_gradient=not diff_out)
        if record:
            t._grad_node = node
            t._output_index = i
        outputs.append(t)

    if node is not None:
        node.finalize(
            out_avals=[(tuple(o.shape), o.dtype) for o in out_list],
            single_output=single,
            inputs=[flat[i] for i in diff_idx],
        )

    if flag("check_nan_inf"):
        _check_nan_inf(name, outputs)

    return outputs[0] if single else tuple(outputs)


def _check_nan_inf(name, outputs):
    """FLAGS_check_nan_inf analog (reference: details/nan_inf_utils_detail,
    hooked into every op run at operator.cc:1270).  Eager: host check.
    Compiled: a device-side finite-reduction feeds a debug callback that
    raises — the compiled-mode debug path the reference gets from its
    per-op nan/inf CUDA kernels."""
    import numpy as np

    for t in outputs:
        v = t._value
        if not jnp.issubdtype(v.dtype, jnp.inexact):
            continue
        if isinstance(v, jax.core.Tracer):
            ok = jnp.isfinite(v.astype(jnp.float32)).all()

            def _host_assert(ok_val, _name=name):
                if not bool(ok_val):
                    raise FloatingPointError(
                        f"op {_name} produced nan/inf (compiled mode)")

            jax.debug.callback(_host_assert, ok)
            continue
        arr = np.asarray(v.astype(jnp.float32))
        if not np.isfinite(arr).all():
            raise FloatingPointError(f"op {name} produced nan/inf")
