"""Shared loader for the native (C++) runtime components.

Each component is a single .cc compiled on first use into a .so next to its
source (g++ -O2 -shared, same contract as the reference's cpp_extension JIT
build — python/paddle/utils/cpp_extension) and bound via ctypes.  Callers
keep a pure-Python fallback so the package works without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_NATIVE_DIR = os.path.dirname(__file__)
_locks: dict = {}
_libs: dict = {}
_guard = threading.Lock()


def load_native(name: str, extra_flags=()):
    """Compile (if stale) and dlopen lib<name>.so from <name>.cc; returns the
    ctypes CDLL.  Raises on compile failure — callers catch and fall back."""
    with _guard:
        lock = _locks.setdefault(name, threading.Lock())
    with lock:
        if name in _libs:
            return _libs[name]
        src = os.path.join(_NATIVE_DIR, f"{name}.cc")
        so = os.path.join(_NATIVE_DIR, f"lib{name}.so")
        if not os.path.exists(so) or (
                os.path.getmtime(src) > os.path.getmtime(so)):
            subprocess.run(
                ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
                 src, "-o", so, *extra_flags],
                check=True, capture_output=True)
        _libs[name] = ctypes.CDLL(so)
        return _libs[name]
