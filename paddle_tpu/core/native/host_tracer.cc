// Host-side trace event recorder.
//
// TPU-native equivalent of the reference's profiler host tracer
// (/root/reference/paddle/fluid/platform/profiler/host_event_recorder.h:
// lock-free per-thread event buffers; host_tracer.cc records RecordEvent
// ranges).  Design: each thread owns a chunked event list guarded only at
// registration/collection time, so ht_begin/ht_end on the hot path are a
// clock read + vector push with no lock contention.  Strings are interned
// once (ht_intern) so events carry a 4-byte id, not a pointer.
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

static inline uint64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct Event {
  uint32_t name_id;
  uint64_t start_ns;
  uint64_t end_ns;
};

struct ThreadBuffer {
  uint64_t tid;
  std::vector<Event> events;
  std::vector<Event> open;  // stack of in-flight ranges
  std::mutex mu;            // taken by owner on push and by collector on drain
};

struct Recorder {
  std::mutex registry_mu;
  std::vector<ThreadBuffer*> buffers;
  std::mutex intern_mu;
  std::unordered_map<std::string, uint32_t> intern;
  std::vector<std::string> names;
  std::atomic<bool> enabled{false};
};

static Recorder g_rec;

static thread_local ThreadBuffer* tl_buf = nullptr;

static ThreadBuffer* buf() {
  if (tl_buf == nullptr) {
    auto* b = new ThreadBuffer();
    // OS thread id: matches python threading.get_native_id(), so native and
    // python-buffered events merge into one per-thread timeline.
    b->tid = (uint64_t)syscall(SYS_gettid);
    std::lock_guard<std::mutex> g(g_rec.registry_mu);
    g_rec.buffers.push_back(b);
    tl_buf = b;
  }
  return tl_buf;
}

}  // namespace

extern "C" {

uint32_t ht_intern(const char* name) {
  std::lock_guard<std::mutex> g(g_rec.intern_mu);
  auto it = g_rec.intern.find(name);
  if (it != g_rec.intern.end()) return it->second;
  uint32_t id = (uint32_t)g_rec.names.size();
  g_rec.names.push_back(name);
  g_rec.intern.emplace(name, id);
  return id;
}

void ht_enable(int on) { g_rec.enabled.store(on != 0); }
int ht_enabled() { return g_rec.enabled.load() ? 1 : 0; }

void ht_begin(uint32_t name_id) {
  if (!g_rec.enabled.load(std::memory_order_relaxed)) return;
  ThreadBuffer* b = buf();
  std::lock_guard<std::mutex> g(b->mu);
  b->open.push_back(Event{name_id, now_ns(), 0});
}

void ht_end() {
  if (!g_rec.enabled.load(std::memory_order_relaxed)) return;
  ThreadBuffer* b = buf();
  std::lock_guard<std::mutex> g(b->mu);
  if (b->open.empty()) return;
  Event e = b->open.back();
  b->open.pop_back();
  e.end_ns = now_ns();
  b->events.push_back(e);
}

// One-shot instant/complete event with explicit timestamps (ns).
void ht_emit(uint32_t name_id, uint64_t start_ns, uint64_t end_ns) {
  ThreadBuffer* b = buf();
  std::lock_guard<std::mutex> g(b->mu);
  b->events.push_back(Event{name_id, start_ns, end_ns});
}

uint64_t ht_now_ns() { return now_ns(); }

// Collection: snapshot all thread buffers (draining them).  Caller first
// asks for the count, then reads into parallel arrays.
static std::vector<Event> g_snapshot;
static std::vector<uint64_t> g_snapshot_tids;

uint64_t ht_snapshot() {
  g_snapshot.clear();
  g_snapshot_tids.clear();
  std::lock_guard<std::mutex> g(g_rec.registry_mu);
  for (ThreadBuffer* b : g_rec.buffers) {
    std::lock_guard<std::mutex> gb(b->mu);
    for (const Event& e : b->events) {
      g_snapshot.push_back(e);
      g_snapshot_tids.push_back(b->tid);
    }
    b->events.clear();
  }
  return g_snapshot.size();
}

void ht_read(uint64_t i, uint32_t* name_id, uint64_t* tid, uint64_t* start_ns,
             uint64_t* end_ns) {
  const Event& e = g_snapshot[i];
  *name_id = e.name_id;
  *tid = g_snapshot_tids[i];
  *start_ns = e.start_ns;
  *end_ns = e.end_ns;
}

// Interned-name lookup; returns bytes copied (0 if id unknown).
uint32_t ht_name(uint32_t id, char* out, uint32_t cap) {
  std::lock_guard<std::mutex> g(g_rec.intern_mu);
  if (id >= g_rec.names.size()) return 0;
  const std::string& s = g_rec.names[id];
  uint32_t n = (uint32_t)s.size() < cap - 1 ? (uint32_t)s.size() : cap - 1;
  std::memcpy(out, s.data(), n);
  out[n] = '\0';
  return n;
}

}  // extern "C"
