// Shared-memory arena for DataLoader worker -> parent tensor transfer.
//
// TPU-native equivalent of the reference's mmap allocator for DataLoader
// shared-memory tensors (/root/reference/paddle/fluid/memory/allocation/
// mmap_allocator.cc, used by fluid/dataloader worker.py): instead of
// pickling ndarray payloads through a pipe, workers memcpy them into a
// POSIX shm arena and send only (offset, shape, dtype) through the queue;
// the parent maps the same arena and wraps the bytes zero-copy.
//
// Allocation is a first-fit free list guarded by a process-shared robust
// mutex living in the arena header, so a crashed worker can't wedge the
// parent (EOWNERDEAD recovers the lock).
#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t kMagic = 0x50414441544D454DULL;  // "PADDATMEM"
constexpr uint32_t kMaxBlocks = 4096;

struct Block {
  uint64_t off;
  uint64_t size;
  uint32_t used;
  uint32_t pad;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;      // payload bytes (after header)
  pthread_mutex_t mu;     // process-shared, robust
  uint32_t n_blocks;
  uint32_t generation;    // bumped when the free list is reset after a crash
  Block blocks[kMaxBlocks];
};

struct Arena {
  Header* h;
  uint8_t* payload;
  uint64_t map_len;
  int fd;
};

// The memmove block-split/coalesce in alloc/free is not atomic: a worker
// killed inside the critical section can leave an inconsistent free list
// (overlapping or lost blocks).  After EOWNERDEAD we must validate before
// allocating again, else two live tensors could share an offset.
static bool list_valid(const Header* h) {
  if (h->n_blocks == 0 || h->n_blocks > kMaxBlocks) return false;
  uint64_t expect = 0;
  for (uint32_t i = 0; i < h->n_blocks; ++i) {
    const Block& b = h->blocks[i];
    if (b.off != expect || b.size == 0) return false;
    expect += b.size;
  }
  return expect == h->capacity;
}

static int lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mu);
    if (!list_valid(h)) {
      // Reset to one free block.  In-flight offsets handed to workers
      // become invalid; the Python transport detects the generation bump
      // and refuses to materialize those refs (possibly-reused bytes).
      h->n_blocks = 1;
      h->blocks[0] = Block{0, h->capacity, 0, 0};
      h->generation++;
    }
    rc = 0;
  }
  return rc;
}

}  // namespace

extern "C" {

// Create (parent) or attach (worker) an arena of `capacity` payload bytes.
void* shm_arena_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale arena from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(Header) + capacity;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Header* h = (Header*)mem;
  std::memset(h, 0, sizeof(Header));
  h->capacity = capacity;
  h->n_blocks = 1;
  h->blocks[0] = Block{0, capacity, 0, 0};
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &attr);
  pthread_mutexattr_destroy(&attr);
  h->magic = kMagic;
  Arena* a = new Arena{h, (uint8_t*)mem + sizeof(Header), total, fd};
  return a;
}

void* shm_arena_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* h = (Header*)mem;
  if (h->magic != kMagic) {
    munmap(mem, st.st_size);
    close(fd);
    return nullptr;
  }
  Arena* a = new Arena{h, (uint8_t*)mem + sizeof(Header), (uint64_t)st.st_size,
                       fd};
  return a;
}

// Returns payload offset or UINT64_MAX when full / fragmented.  gen_out
// (optional) receives the free-list generation observed UNDER the mutex —
// the only sample that is race-free against a concurrent crash reset.
uint64_t shm_arena_alloc2(void* arena, uint64_t size, uint32_t* gen_out) {
  Arena* a = (Arena*)arena;
  Header* h = a->h;
  size = (size + 63) & ~63ULL;  // 64B alignment
  if (size == 0) size = 64;
  if (lock(h) != 0) return UINT64_MAX;
  if (gen_out) *gen_out = h->generation;
  uint64_t got = UINT64_MAX;
  for (uint32_t i = 0; i < h->n_blocks; ++i) {
    Block& b = h->blocks[i];
    if (b.used || b.size < size) continue;
    if (b.size > size && h->n_blocks < kMaxBlocks) {  // split
      std::memmove(&h->blocks[i + 2], &h->blocks[i + 1],
                   (h->n_blocks - i - 1) * sizeof(Block));
      h->blocks[i + 1] = Block{b.off + size, b.size - size, 0, 0};
      b.size = size;
      h->n_blocks++;
    }
    b.used = 1;
    got = b.off;
    break;
  }
  pthread_mutex_unlock(&h->mu);
  return got;
}

uint64_t shm_arena_alloc(void* arena, uint64_t size) {
  return shm_arena_alloc2(arena, size, nullptr);
}

int shm_arena_free(void* arena, uint64_t off) {
  Arena* a = (Arena*)arena;
  Header* h = a->h;
  if (lock(h) != 0) return -1;
  int rc = -1;
  for (uint32_t i = 0; i < h->n_blocks; ++i) {
    if (h->blocks[i].off != off || !h->blocks[i].used) continue;
    h->blocks[i].used = 0;
    // coalesce with right then left neighbour
    if (i + 1 < h->n_blocks && !h->blocks[i + 1].used) {
      h->blocks[i].size += h->blocks[i + 1].size;
      std::memmove(&h->blocks[i + 1], &h->blocks[i + 2],
                   (h->n_blocks - i - 2) * sizeof(Block));
      h->n_blocks--;
    }
    if (i > 0 && !h->blocks[i - 1].used) {
      h->blocks[i - 1].size += h->blocks[i].size;
      std::memmove(&h->blocks[i], &h->blocks[i + 1],
                   (h->n_blocks - i - 1) * sizeof(Block));
      h->n_blocks--;
    }
    rc = 0;
    break;
  }
  pthread_mutex_unlock(&h->mu);
  return rc;
}

// Raw pointer to payload at offset (valid while the mapping lives).
void* shm_arena_ptr(void* arena, uint64_t off) {
  Arena* a = (Arena*)arena;
  return a->payload + off;
}

void shm_arena_write(void* arena, uint64_t off, const void* src, uint64_t n) {
  Arena* a = (Arena*)arena;
  std::memcpy(a->payload + off, src, n);
}

void shm_arena_read(void* arena, uint64_t off, void* dst, uint64_t n) {
  Arena* a = (Arena*)arena;
  std::memcpy(dst, a->payload + off, n);
}

uint64_t shm_arena_capacity(void* arena) { return ((Arena*)arena)->h->capacity; }

// Current free-list generation; bumped when a crash forced a reset.  Refs
// allocated under an older generation must not be trusted.
uint32_t shm_arena_generation(void* arena) {
  return ((Arena*)arena)->h->generation;
}

// Bytes currently allocated (diagnostics / tests).
uint64_t shm_arena_used(void* arena) {
  Arena* a = (Arena*)arena;
  Header* h = a->h;
  if (lock(h) != 0) return 0;
  uint64_t used = 0;
  for (uint32_t i = 0; i < h->n_blocks; ++i)
    if (h->blocks[i].used) used += h->blocks[i].size;
  pthread_mutex_unlock(&h->mu);
  return used;
}

void shm_arena_detach(void* arena) {
  Arena* a = (Arena*)arena;
  munmap((void*)a->h, a->map_len);
  close(a->fd);
  delete a;
}

void shm_arena_destroy(void* arena, const char* name) {
  shm_arena_detach(arena);
  shm_unlink(name);
}

}  // extern "C"
