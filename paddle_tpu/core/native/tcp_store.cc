// TCPStore: rendezvous key-value store for multi-host bootstrap.
//
// Native analog of the reference's C++ TCPStore
// (/root/reference/paddle/fluid/distributed/store/tcp_store.h:91,
// tcp_utils.cc): a TCP server on the master rank serving set/get/add/wait,
// used before any accelerator interconnect exists.  C ABI for ctypes.
//
// Protocol (all ints little-endian u32 unless noted):
//   request : u8 cmd | u32 keylen | key | (SET: u32 vallen | val)
//                                        (ADD: i64 delta)
//                                        (WAIT: u32 timeout_ms)
//   response: GET -> u32 vallen|val (vallen==0xFFFFFFFF => missing)
//             SET -> u8 1
//             ADD -> i64 new_value
//             WAIT-> u8 (1 found, 0 timeout)

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Cmd : uint8_t { kSet = 1, kGet = 2, kAdd = 3, kWait = 4, kDelete = 5 };

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, p + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd, p + sent, n - sent, 0);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

class Store {
 public:
  void set(const std::string& k, std::string v) {
    {
      std::lock_guard<std::mutex> g(mu_);
      data_[k] = std::move(v);
    }
    cv_.notify_all();
  }

  bool get(const std::string& k, std::string* out) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = data_.find(k);
    if (it == data_.end()) return false;
    *out = it->second;
    return true;
  }

  int64_t add(const std::string& k, int64_t delta) {
    int64_t result;
    {
      std::lock_guard<std::mutex> g(mu_);
      int64_t cur = 0;
      auto it = data_.find(k);
      if (it != data_.end() && it->second.size() == sizeof(int64_t)) {
        std::memcpy(&cur, it->second.data(), sizeof(int64_t));
      }
      cur += delta;
      std::string v(sizeof(int64_t), '\0');
      std::memcpy(&v[0], &cur, sizeof(int64_t));
      data_[k] = std::move(v);
      result = cur;
    }
    cv_.notify_all();
    return result;
  }

  bool wait(const std::string& k, uint32_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    return cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                        [&] { return data_.count(k) > 0; });
  }

  void erase(const std::string& k) {
    std::lock_guard<std::mutex> g(mu_);
    data_.erase(k);
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
};

struct Server {
  int listen_fd = -1;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> conns;
  std::mutex conns_mu;
  Store store;

  ~Server() { shutdown(); }

  void shutdown() {
    bool expected = false;
    if (!stop.compare_exchange_strong(expected, true)) return;
    if (listen_fd >= 0) ::shutdown(listen_fd, SHUT_RDWR), ::close(listen_fd);
    if (accept_thread.joinable()) accept_thread.join();
    std::lock_guard<std::mutex> g(conns_mu);
    for (auto& t : conns)
      if (t.joinable()) t.join();
  }
};

void handle_conn(Server* srv, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint8_t cmd;
    if (!read_full(fd, &cmd, 1)) break;
    uint32_t keylen;
    if (!read_full(fd, &keylen, 4)) break;
    std::string key(keylen, '\0');
    if (keylen && !read_full(fd, &key[0], keylen)) break;
    if (cmd == kSet) {
      uint32_t vallen;
      if (!read_full(fd, &vallen, 4)) break;
      std::string val(vallen, '\0');
      if (vallen && !read_full(fd, &val[0], vallen)) break;
      srv->store.set(key, std::move(val));
      uint8_t ok = 1;
      if (!write_full(fd, &ok, 1)) break;
    } else if (cmd == kGet) {
      std::string val;
      if (srv->store.get(key, &val)) {
        uint32_t n = static_cast<uint32_t>(val.size());
        if (!write_full(fd, &n, 4) || !write_full(fd, val.data(), n)) break;
      } else {
        uint32_t n = 0xFFFFFFFFu;
        if (!write_full(fd, &n, 4)) break;
      }
    } else if (cmd == kAdd) {
      int64_t delta;
      if (!read_full(fd, &delta, 8)) break;
      int64_t result = srv->store.add(key, delta);
      if (!write_full(fd, &result, 8)) break;
    } else if (cmd == kWait) {
      uint32_t timeout_ms;
      if (!read_full(fd, &timeout_ms, 4)) break;
      uint8_t found = srv->store.wait(key, timeout_ms) ? 1 : 0;
      if (!write_full(fd, &found, 1)) break;
    } else if (cmd == kDelete) {
      srv->store.erase(key);
      uint8_t ok = 1;
      if (!write_full(fd, &ok, 1)) break;
    } else {
      break;
    }
  }
  ::close(fd);
}

struct Client {
  int fd = -1;
  std::mutex mu;
  ~Client() {
    if (fd >= 0) ::close(fd);
  }
};

}  // namespace

extern "C" {

void* tcp_store_server_start(int port) {
  auto* srv = new Server();
  srv->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (srv->listen_fd < 0) {
    delete srv;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(srv->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(srv->listen_fd, 128) != 0) {
    ::close(srv->listen_fd);
    delete srv;
    return nullptr;
  }
  srv->accept_thread = std::thread([srv] {
    while (!srv->stop.load()) {
      int fd = ::accept(srv->listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      std::lock_guard<std::mutex> g(srv->conns_mu);
      srv->conns.emplace_back(handle_conn, srv, fd);
    }
  });
  return srv;
}

void tcp_store_server_stop(void* handle) {
  auto* srv = static_cast<Server*>(handle);
  if (srv) {
    srv->shutdown();
    delete srv;
  }
}

void* tcp_store_client_connect(const char* host, int port, int timeout_ms) {
  auto* cl = new Client();
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    cl->fd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    ::inet_pton(AF_INET, host, &addr.sin_addr);
    if (::connect(cl->fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) ==
        0) {
      int one = 1;
      ::setsockopt(cl->fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return cl;
    }
    ::close(cl->fd);
    cl->fd = -1;
    if (std::chrono::steady_clock::now() > deadline) {
      delete cl;
      return nullptr;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void tcp_store_client_close(void* handle) {
  delete static_cast<Client*>(handle);
}

static bool send_key(Client* cl, uint8_t cmd, const char* key,
                     uint32_t keylen) {
  return write_full(cl->fd, &cmd, 1) && write_full(cl->fd, &keylen, 4) &&
         write_full(cl->fd, key, keylen);
}

int tcp_store_set(void* handle, const char* key, const uint8_t* val,
                  uint32_t vallen) {
  auto* cl = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> g(cl->mu);
  if (!send_key(cl, kSet, key, static_cast<uint32_t>(strlen(key)))) return -1;
  if (!write_full(cl->fd, &vallen, 4) || !write_full(cl->fd, val, vallen))
    return -1;
  uint8_t ok;
  return read_full(cl->fd, &ok, 1) && ok == 1 ? 0 : -1;
}

// returns value length, -1 missing, -2 error; copies at most buflen bytes.
int64_t tcp_store_get(void* handle, const char* key, uint8_t* buf,
                      uint32_t buflen) {
  auto* cl = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> g(cl->mu);
  if (!send_key(cl, kGet, key, static_cast<uint32_t>(strlen(key)))) return -2;
  uint32_t n;
  if (!read_full(cl->fd, &n, 4)) return -2;
  if (n == 0xFFFFFFFFu) return -1;
  std::string val(n, '\0');
  if (n && !read_full(cl->fd, &val[0], n)) return -2;
  std::memcpy(buf, val.data(), n < buflen ? n : buflen);
  return static_cast<int64_t>(n);
}

int64_t tcp_store_add(void* handle, const char* key, int64_t delta) {
  auto* cl = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> g(cl->mu);
  if (!send_key(cl, kAdd, key, static_cast<uint32_t>(strlen(key))))
    return INT64_MIN;
  if (!write_full(cl->fd, &delta, 8)) return INT64_MIN;
  int64_t result;
  if (!read_full(cl->fd, &result, 8)) return INT64_MIN;
  return result;
}

int tcp_store_wait(void* handle, const char* key, uint32_t timeout_ms) {
  auto* cl = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> g(cl->mu);
  if (!send_key(cl, kWait, key, static_cast<uint32_t>(strlen(key))))
    return -1;
  if (!write_full(cl->fd, &timeout_ms, 4)) return -1;
  uint8_t found;
  if (!read_full(cl->fd, &found, 1)) return -1;
  return found ? 1 : 0;
}

int tcp_store_delete(void* handle, const char* key) {
  auto* cl = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> g(cl->mu);
  if (!send_key(cl, kDelete, key, static_cast<uint32_t>(strlen(key))))
    return -1;
  uint8_t ok;
  return read_full(cl->fd, &ok, 1) && ok == 1 ? 0 : -1;
}

}  // extern "C"
