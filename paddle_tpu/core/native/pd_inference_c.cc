// Minimal stable C inference ABI over the paddle_tpu Predictor
// (reference: paddle/fluid/inference/capi_exp/pd_inference_api.h — the
// C surface external serving stacks and the Go bindings link against).
//
// TPU-native design: the predictor is the Python/XLA serving runtime
// (paddle_tpu/inference), so this shim embeds CPython — inside an
// existing Python process (ctypes consumers) it joins the running
// interpreter via the GIL; inside a plain C program it initializes one.
// Float32 single-input/single-output convenience Run covers the
// predictor round trip; richer IO goes through the Python API.
//
// Build: g++ -shared -fPIC pd_inference_c.cc $(python3-config --includes)
//        -lpython3.X   (paddle_tpu/core/native/build.py does this)
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

std::string g_last_error;  // guarded by the GIL in practice

struct GIL {
  PyGILState_STATE state;
  GIL() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // Py_InitializeEx leaves this thread HOLDING the GIL; release it
      // so other threads of a multithreaded C consumer can Ensure —
      // otherwise their first call deadlocks forever
      PyEval_SaveThread();
    }
    state = PyGILState_Ensure();
  }
  ~GIL() { PyGILState_Release(state); }
};

void capture_py_error(const char* where) {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  g_last_error = where;
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* text = PyUnicode_AsUTF8(s);
      if (text == nullptr) {  // non-UTF-8 message: report what we can
        PyErr_Clear();
        text = "<error text not UTF-8 encodable>";
      }
      g_last_error += ": ";
      g_last_error += text;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// run helper compiled once into __main__-independent globals: keeps the
// C side free of the numpy C API
const char* kHelperSrc = R"PY(
import numpy as _np

def _pd_capi_create(prog_file):
    from paddle_tpu import inference
    cfg = inference.Config(prog_file)
    return inference.create_predictor(cfg)

def _pd_capi_run(pred, buf, shape):
    x = _np.frombuffer(buf, dtype=_np.float32).reshape(shape).copy()
    outs = pred.run([x])
    o = outs[0]
    o = _np.asarray(o.numpy() if hasattr(o, "numpy") else o,
                    dtype=_np.float32)
    return o.tobytes(), list(o.shape)
)PY";

PyObject* helper_globals() {
  static PyObject* globals = nullptr;
  if (globals == nullptr) {
    globals = PyDict_New();
    PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
    PyObject* r = PyRun_String(kHelperSrc, Py_file_input, globals, globals);
    if (r == nullptr) {
      capture_py_error("helper compile failed");
      Py_CLEAR(globals);
      return nullptr;
    }
    Py_DECREF(r);
  }
  return globals;
}

}  // namespace

extern "C" {

typedef struct PD_Config {
  std::string prog_file;
} PD_Config;

typedef struct PD_Predictor {
  PyObject* pred;  // owned reference
} PD_Predictor;

PD_Config* PD_ConfigCreate() { return new PD_Config(); }

void PD_ConfigSetModel(PD_Config* cfg, const char* prog_file,
                       const char* params_file) {
  (void)params_file;  // jit-saved artifacts bundle weights
  if (cfg != nullptr && prog_file != nullptr) cfg->prog_file = prog_file;
}

void PD_ConfigDestroy(PD_Config* cfg) { delete cfg; }

const char* PD_GetLastError() { return g_last_error.c_str(); }

PD_Predictor* PD_PredictorCreate(PD_Config* cfg) {
  if (cfg == nullptr) {
    g_last_error = "null config";
    return nullptr;
  }
  GIL gil;
  PyObject* globals = helper_globals();
  if (globals == nullptr) return nullptr;
  PyObject* fn = PyDict_GetItemString(globals, "_pd_capi_create");
  if (fn == nullptr) {
    g_last_error = "helper module lacks _pd_capi_create";
    return nullptr;
  }
  PyObject* pred =
      PyObject_CallFunction(fn, "s", cfg->prog_file.c_str());
  if (pred == nullptr) {
    capture_py_error("PD_PredictorCreate");
    return nullptr;
  }
  PD_Predictor* out = new PD_Predictor();
  out->pred = pred;
  return out;
}

void PD_PredictorDestroy(PD_Predictor* p) {
  if (p == nullptr) return;
  GIL gil;
  Py_XDECREF(p->pred);
  delete p;
}

void PD_BufferFree(void* buf) { free(buf); }

// Run the predictor on ONE float32 tensor; returns 0 on success.  The
// out_data/out_shape buffers are malloc'd — release with PD_BufferFree.
int PD_PredictorRunFloat(PD_Predictor* p, const float* data,
                         const int64_t* shape, int ndim, float** out_data,
                         int64_t** out_shape, int* out_ndim) {
  if (p == nullptr || p->pred == nullptr) {
    g_last_error = "null predictor";
    return 1;
  }
  GIL gil;
  PyObject* globals = helper_globals();
  if (globals == nullptr) return 1;

  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) {
    if (shape[i] < 0) {
      g_last_error =
          "negative shape dimension: PD_PredictorRunFloat needs a "
          "concrete shape (dynamic -1 dims are a Python-API feature)";
      return 1;
    }
    n *= shape[i];
  }
  PyObject* pyshape = PyList_New(ndim);
  if (pyshape == nullptr) {
    capture_py_error("PD_PredictorRunFloat: shape alloc");
    return 1;
  }
  for (int i = 0; i < ndim; ++i) {
    PyList_SetItem(pyshape, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject* buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), n * sizeof(float));
  if (buf == nullptr) {
    Py_DECREF(pyshape);
    capture_py_error("PD_PredictorRunFloat: input buffer");
    return 1;
  }
  PyObject* fn = PyDict_GetItemString(globals, "_pd_capi_run");
  if (fn == nullptr) {
    Py_DECREF(buf);
    Py_DECREF(pyshape);
    g_last_error = "helper module lacks _pd_capi_run";
    return 1;
  }
  PyObject* res = PyObject_CallFunctionObjArgs(fn, p->pred, buf, pyshape,
                                               nullptr);
  Py_DECREF(buf);
  Py_DECREF(pyshape);
  if (res == nullptr) {
    capture_py_error("PD_PredictorRunFloat");
    return 1;
  }
  // contract with the helper: a 2-tuple of (bytes payload, dims list)
  if (!PyTuple_Check(res) || PyTuple_Size(res) != 2) {
    Py_DECREF(res);
    g_last_error =
        "_pd_capi_run returned a malformed result (expected "
        "(bytes, dims) 2-tuple)";
    return 1;
  }
  PyObject* out_bytes = PyTuple_GetItem(res, 0);
  PyObject* out_dims = PyTuple_GetItem(res, 1);
  if (!PyBytes_Check(out_bytes) || !PyList_Check(out_dims)) {
    Py_DECREF(res);
    g_last_error =
        "_pd_capi_run returned a malformed result (expected "
        "(bytes, dims) 2-tuple)";
    return 1;
  }
  Py_ssize_t nbytes = PyBytes_Size(out_bytes);
  *out_data = static_cast<float*>(malloc(nbytes));
  if (*out_data == nullptr) {
    Py_DECREF(res);
    g_last_error = "out of memory allocating output buffer";
    return 1;
  }
  std::memcpy(*out_data, PyBytes_AsString(out_bytes), nbytes);
  Py_ssize_t od = PyList_Size(out_dims);
  *out_ndim = static_cast<int>(od);
  *out_shape = static_cast<int64_t*>(malloc(od * sizeof(int64_t)));
  if (*out_shape == nullptr) {
    free(*out_data);
    *out_data = nullptr;
    Py_DECREF(res);
    g_last_error = "out of memory allocating shape buffer";
    return 1;
  }
  for (Py_ssize_t i = 0; i < od; ++i) {
    (*out_shape)[i] = PyLong_AsLongLong(PyList_GetItem(out_dims, i));
  }
  Py_DECREF(res);
  return 0;
}

}  // extern "C"
