"""Global flag system.

Capability parity with the reference's exported gflags
(/root/reference/paddle/fluid/platform/flags.cc, surfaced via
global_value_getter_setter.cc and FLAGS_* env vars): one typed registry,
settable via paddle_tpu.set_flags or FLAGS_<name> environment variables.
"""
from __future__ import annotations

import os
from typing import Any, Dict


class _Flag:
    __slots__ = ("name", "value", "default", "type", "help")

    def __init__(self, name, default, help=""):
        self.name = name
        self.default = default
        self.type = type(default)
        self.help = help
        env = os.environ.get(f"FLAGS_{name}")
        self.value = self._parse(env) if env is not None else default

    def _parse(self, text: str):
        if self.type is bool:
            return text.lower() in ("1", "true", "yes", "on")
        return self.type(text)


_REGISTRY: Dict[str, _Flag] = {}


def define_flag(name: str, default, help: str = ""):
    if name not in _REGISTRY:
        _REGISTRY[name] = _Flag(name, default, help)
    return _REGISTRY[name]


def _canon(name: str) -> str:
    # paddle.get_flags/set_flags take "FLAGS_<name>" keys; the registry
    # stores bare names.  Accept both.
    return name[6:] if name.startswith("FLAGS_") else name


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    return {n: _REGISTRY[_canon(n)].value for n in names}


def set_flags(flags: Dict[str, Any]):
    for name, value in flags.items():
        name = _canon(name)
        if name not in _REGISTRY:
            raise KeyError(f"unknown flag {name!r}")
        flag = _REGISTRY[name]
        flag.value = flag._parse(value) if isinstance(value, str) else flag.type(value)


def flag(name: str):
    return _REGISTRY[name].value


# Core flags (reference: platform/flags.cc).
define_flag("check_nan_inf", False, "check every op output for nan/inf")
define_flag("eager_op_jit", True, "jit-compile eager per-op computations")
define_flag("allocator_strategy", "auto_growth", "kept for API parity; XLA owns HBM")
define_flag("use_pallas_kernels", True, "use Pallas kernels for fused ops on TPU")
define_flag("use_autotune", False, "search + cache kernel tile sizes "
            "(reference: phi/kernels/autotune switch_autotune)")
define_flag("use_fused_serving", True,
            "fused paged-attention decode + RMSNorm->matmul epilogues on "
            "the serving hot path (TPU default; CPU runs the XLA fallback "
            "only when forced via ServingConfig(fused_kernels=True))")
define_flag("benchmark", False, "synchronize after every op (timing mode)")
define_flag("flash_block_q", 0,
            "override flash-attention q-block size (0 = default/autotune)")
define_flag("flash_block_k", 0,
            "override flash-attention k-block size (0 = default/autotune)")
define_flag("heter_max_payload_mb", 64,
            "cap (MiB) on a single array moved through the TCPStore by the "
            "heter gateway; large gradients belong on XLA collectives "
            "(reference rides Gloo here, ProcessGroupHeter.h:64)")
define_flag("heter_chunk_mb", 1,
            "chunk size (MiB) for store-routed heter payloads; 1 MiB "
            "fits the TCPStore client's probe buffer in one RPC")
define_flag("tracer_mkldnn_ops_on", "", "parity stub")
define_flag("max_inplace_grad_add", 0, "parity stub")
