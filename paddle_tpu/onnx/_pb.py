"""protoc-generated bindings for onnx.proto, built on first use.

Mirrors the repo's native-build pattern (core/native/build.py): the
generated module is cached next to a hash of the .proto so schema edits
regenerate automatically.  protoc is part of the base toolchain.
"""
from __future__ import annotations

import hashlib
import importlib.util
import os
import subprocess
import sys

_MOD = None


def _cache_dir() -> str:
    root = os.environ.get("PADDLE_TPU_CACHE",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "paddle_tpu"))
    d = os.path.join(root, "onnx_pb")
    os.makedirs(d, exist_ok=True)
    return d


def get() -> "module":
    """The generated onnx_pb2 module (ModelProto, GraphProto, ...)."""
    global _MOD
    if _MOD is not None:
        return _MOD
    proto = os.path.join(os.path.dirname(__file__), "onnx.proto")
    src = open(proto, "rb").read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    d = _cache_dir()
    gen = os.path.join(d, f"onnx_pb2_{tag}.py")
    if not os.path.exists(gen):
        tmp = os.path.join(d, "_build")
        os.makedirs(tmp, exist_ok=True)
        subprocess.run(
            ["protoc", f"--proto_path={os.path.dirname(proto)}",
             f"--python_out={tmp}", os.path.basename(proto)],
            check=True, capture_output=True)
        os.replace(os.path.join(tmp, "onnx_pb2.py"), gen)
    spec = importlib.util.spec_from_file_location("paddle_tpu_onnx_pb2", gen)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_onnx_pb2"] = mod
    spec.loader.exec_module(mod)
    _MOD = mod
    return mod
