# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.onnx (reference: python/paddle/onnx/export.py -> paddle2onnx).

TPU-native design: the reference shells out to the external paddle2onnx
converter over a static Program; here the model is traced to a jaxpr
(the same static-shape tracing contract as jit.to_static) and converted
in-tree to an ONNX ModelProto (converter.py), serialized with
protoc-generated bindings (_pb.py).  Model parameters are embedded as
initializers, so the .onnx file is self-contained and loads in
onnxruntime/netron.  reference_runtime.py can execute the exported
subset with numpy for verification without onnxruntime.
"""
from __future__ import annotations

import numpy as np

from . import _pb, converter, reference_runtime  # noqa: F401
from .reference_runtime import run_model  # noqa: F401


def _example_array(spec):
    from ..core.tensor import Tensor
    from ..static import InputSpec

    if isinstance(spec, Tensor):
        return np.asarray(spec.numpy())
    if isinstance(spec, InputSpec):
        shape = [1 if (s is None or int(s) < 0) else int(s)
                 for s in spec.shape]
        from ..core.dtype import to_np

        return np.zeros(shape, to_np(spec.dtype) if spec.dtype else
                        np.float32)
    if isinstance(spec, np.ndarray):
        return spec
    raise TypeError(f"input_spec entries must be InputSpec/Tensor, got "
                    f"{type(spec)}")


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export a Layer (or callable) to `path + '.onnx'`.

    Matches the reference signature (python/paddle/onnx/export.py): the
    saved file is `path` with the `.onnx` suffix appended, input_spec
    gives shapes/dtypes (unknown dims become 1 — the exporter is
    static-shape like the rest of the XLA pipeline).
    Returns the file path."""
    import jax

    from ..core.tensor import Tensor

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec (shapes are "
                         "static under tracing)")
    examples = [_example_array(s) for s in input_spec]

    def fn(*arrays):
        outs = layer(*[Tensor(a) for a in arrays])
        if isinstance(outs, (list, tuple)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in outs)
        return outs._value if isinstance(outs, Tensor) else outs

    training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        closed = jax.make_jaxpr(fn)(*examples)
    finally:
        if training and hasattr(layer, "train"):
            layer.train()

    names = []
    for i, s in enumerate(input_spec):
        n = getattr(s, "name", None)
        names.append(n if n else f"input_{i}")
    conv = converter.Converter(opset=int(opset_version))
    model = conv.convert(closed, names,
                         graph_name=type(layer).__name__)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model.SerializeToString())
    return out_path
