"""paddle.onnx (reference: python/paddle/onnx/export.py → paddle2onnx).

ONNX export from StableHLO needs an external converter not present in this
environment; jit.save's StableHLO artifact is the portable format.
"""


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export unavailable (no paddle2onnx equivalent in-image); use "
        "paddle_tpu.jit.save — the serialized StableHLO artifact is portable "
        "across PJRT runtimes")
