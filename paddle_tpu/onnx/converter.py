# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""jaxpr -> ONNX GraphProto conversion.

The exporter traces the model with `jax.make_jaxpr` (static shapes, the
same tracing contract as jit.to_static) and maps each jaxpr primitive to
ONNX ops (default opset 13).  Model parameters enter the jaxpr as consts
and become ONNX initializers, so the exported file is self-contained.

Reference behavior being replaced: python/paddle/onnx/export.py delegates
to the external paddle2onnx converter over a static Program; here the
traced jaxpr plays the Program's role and the converter is in-tree.
"""
from __future__ import annotations

import numpy as np

from . import _pb

_DTYPE = {
    "float32": 1, "uint8": 2, "int8": 3, "uint16": 4, "int16": 5,
    "int32": 6, "int64": 7, "bool": 9, "float16": 10, "float64": 11,
    "uint32": 12, "uint64": 13, "bfloat16": 16,
}


def _onnx_dtype(np_dtype) -> int:
    name = np.dtype(np_dtype).name if np.dtype(np_dtype).name in _DTYPE \
        else str(np_dtype)
    try:
        return _DTYPE[name]
    except KeyError:
        raise NotImplementedError(f"ONNX export: unsupported dtype {np_dtype}")


def _tensor_proto(pb, name, arr):
    arr = np.asarray(arr)
    t = pb.TensorProto()
    t.name = name
    t.dims.extend(arr.shape)
    t.data_type = 16 if str(arr.dtype) == "bfloat16" \
        else _onnx_dtype(arr.dtype)
    t.raw_data = np.ascontiguousarray(arr).tobytes()
    return t


class _Graph:
    """Accumulates nodes/initializers with unique value names."""

    def __init__(self, pb, opset):
        self.pb = pb
        self.opset = opset
        self.nodes = []
        self.initializers = {}
        self._n = 0

    def fresh(self, hint="v"):
        self._n += 1
        return f"{hint}_{self._n}"

    def init(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers[name] = _tensor_proto(self.pb, name, arr)
        return name

    def node(self, op_type, inputs, n_out=1, out_names=None, **attrs):
        node = self.pb.NodeProto()
        node.op_type = op_type
        node.name = self.fresh(op_type)
        node.input.extend(inputs)
        outs = out_names or [self.fresh(op_type.lower()) for _ in range(n_out)]
        node.output.extend(outs)
        for k, v in attrs.items():
            a = node.attribute.add()
            a.name = k
            if isinstance(v, float):
                a.type, a.f = self.pb.AttributeProto.FLOAT, v
            elif isinstance(v, bool) or isinstance(v, (int, np.integer)):
                a.type, a.i = self.pb.AttributeProto.INT, int(v)
            elif isinstance(v, str):
                a.type, a.s = self.pb.AttributeProto.STRING, v.encode()
            elif isinstance(v, (list, tuple)):
                if v and isinstance(v[0], float):
                    a.type = self.pb.AttributeProto.FLOATS
                    a.floats.extend(v)
                else:
                    a.type = self.pb.AttributeProto.INTS
                    a.ints.extend(int(x) for x in v)
            else:
                raise TypeError(f"attr {k}={v!r}")
        self.nodes.append(node)
        return outs[0] if n_out == 1 else outs


# --- primitive handlers ----------------------------------------------------
# each: fn(g, eqn, in_names) -> out_name(s)

_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "logistic": "Sigmoid",
    "sqrt": "Sqrt", "abs": "Abs", "neg": "Neg", "sign": "Sign",
    "floor": "Floor", "ceil": "Ceil", "round": "Round", "erf": "Erf",
    "sin": "Sin", "cos": "Cos", "tan": "Tan", "asin": "Asin",
    "acos": "Acos", "atan": "Atan", "sinh": "Sinh", "cosh": "Cosh",
    "not": "Not", "and": "And", "or": "Or", "xor": "Xor",
}

_COMPARE = {"eq": "Equal", "lt": "Less", "le": "LessOrEqual",
            "gt": "Greater", "ge": "GreaterOrEqual"}

_REDUCE_ATTR = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
                "reduce_prod": "ReduceProd"}


def _dot_general(g, eqn, ins):
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars
    ln, rn = len(lhs.aval.shape), len(rhs.aval.shape)
    letters = iter("abcdefghijklmnopqrstuvwxyz")
    l_sub = [None] * ln
    r_sub = [None] * rn
    for i, j in zip(lb, rb):
        c = next(letters)
        l_sub[i] = c
        r_sub[j] = c
    for i, j in zip(lc, rc):
        c = next(letters)
        l_sub[i] = c
        r_sub[j] = c
    l_free = []
    for i in range(ln):
        if l_sub[i] is None:
            l_sub[i] = next(letters)
            l_free.append(l_sub[i])
    r_free = []
    for j in range(rn):
        if r_sub[j] is None:
            r_sub[j] = next(letters)
            r_free.append(r_sub[j])
    out_sub = [l_sub[i] for i in lb] + l_free + r_free
    eqstr = f"{''.join(l_sub)},{''.join(r_sub)}->{''.join(out_sub)}"
    return g.node("Einsum", ins, equation=eqstr)


def _conv(g, eqn, ins):
    p = eqn.params
    dn = p["dimension_numbers"]
    lhs_spec, rhs_spec, out_spec = dn
    nsp = len(lhs_spec) - 2
    # transpose input to NC<spatial>, kernel to OI<spatial>
    x = g.node("Transpose", [ins[0]], perm=list(lhs_spec))
    w = g.node("Transpose", [ins[1]], perm=list(rhs_spec))
    pads_lo = [int(lo) for lo, _ in p["padding"]]
    pads_hi = [int(hi) for _, hi in p["padding"]]
    lhs_dil = [int(d) for d in p["lhs_dilation"]]
    in_shape = [int(s) for s in eqn.invars[0].aval.shape]
    sp = [in_shape[lhs_spec[2 + i]] for i in range(nsp)]
    if any(d != 1 for d in lhs_dil):
        # transposed conv (conv2d_transpose lowers to conv_general_dilated
        # with lhs_dilation = stride; the kernel flip is an upstream rev
        # eqn by jaxpr time).  ONNX has no lhs_dilation, so zero-stuff the
        # input explicitly: [..,S,..] -> [..,S,1,..] -> pad -> [..,S*L,..]
        # -> slice off the (L-1) trailing zeros -> plain Conv.
        n_b, c_in = in_shape[lhs_spec[0]], in_shape[lhs_spec[1]]
        inter = [n_b, c_in]
        for s in sp:
            inter += [s, 1]
        x = g.node("Reshape", [x, g.init(
            np.asarray(inter, np.int64), "stuff_shape")])
        ndim = 2 + 2 * nsp
        pad_vec = [0] * (2 * ndim)
        for i, d in enumerate(lhs_dil):
            pad_vec[ndim + 3 + 2 * i] = d - 1  # after-pad the 1-dims
        x = g.node("Pad", [x, g.init(
            np.asarray(pad_vec, np.int64), "stuff_pads")])
        x = g.node("Reshape", [x, g.init(np.asarray(
            [n_b, c_in] + [s * d for s, d in zip(sp, lhs_dil)],
            np.int64), "stuffed")])
        sp = [(s - 1) * d + 1 for s, d in zip(sp, lhs_dil)]
        x = g.node("Slice", [
            x,
            g.init(np.asarray([0] * nsp, np.int64), "st"),
            g.init(np.asarray(sp, np.int64), "en"),
            g.init(np.asarray([2 + i for i in range(nsp)], np.int64),
                   "ax"),
            g.init(np.asarray([1] * nsp, np.int64), "sp")])
    if any(v < 0 for v in pads_lo + pads_hi):
        # XLA allows negative conv padding (transposed conv with padding
        # > kernel-1); ONNX Conv does not — crop with Slice first
        starts = [max(0, -lo) for lo in pads_lo]
        ends = [s - max(0, -hi) for s, hi in zip(sp, pads_hi)]
        x = g.node("Slice", [
            x,
            g.init(np.asarray(starts, np.int64), "nst"),
            g.init(np.asarray(ends, np.int64), "nen"),
            g.init(np.asarray([2 + i for i in range(nsp)], np.int64),
                   "nax"),
            g.init(np.asarray([1] * nsp, np.int64), "nsp")])
        pads_lo = [max(0, v) for v in pads_lo]
        pads_hi = [max(0, v) for v in pads_hi]
    out = g.node(
        "Conv", [x, w],
        strides=[int(s) for s in p["window_strides"]],
        pads=pads_lo + pads_hi,
        dilations=[int(d) for d in p["rhs_dilation"]],
        group=int(p["feature_group_count"]))
    # out currently NC<spatial>; permute to out_spec
    inv = [0] * (nsp + 2)
    for pos, axis in enumerate(out_spec):
        inv[axis] = pos
    return g.node("Transpose", [out], perm=inv)


def _pool(g, eqn, ins, kind):
    p = eqn.params
    win = list(p["window_dimensions"])
    strides = list(p["window_strides"])
    padding = list(p["padding"])
    w_dil = list(p.get("window_dilation", [1] * len(win)))
    if any(d != 1 for d in p.get("base_dilation", [1] * len(win))):
        raise NotImplementedError("ONNX export: base-dilated pooling")
    if win[0] != 1 or win[1] != 1 or w_dil[0] != 1 or w_dil[1] != 1:
        raise NotImplementedError(
            "ONNX export: reduce_window over batch/channel dims")
    kernel = [int(w) for w in win[2:]]
    dil = [int(d) for d in w_dil[2:]]
    pads_lo = [int(lo) for lo, _ in padding[2:]]
    pads_hi = [int(hi) for _, hi in padding[2:]]
    attrs = dict(kernel_shape=kernel, strides=[int(s) for s in strides[2:]],
                 pads=pads_lo + pads_hi)
    if any(d != 1 for d in dil):
        if kind != "max":
            # AveragePool only gained `dilations` at opset 19; this
            # converter declares <= 17, so emitting it would produce a
            # schema-invalid file that only the in-tree runtime accepts
            raise NotImplementedError(
                "ONNX export: dilated sum/avg pooling needs opset 19 "
                "(AveragePool dilations); only dilated MaxPool is "
                "supported at the declared opset")
        # ONNX MaxPool dilations attribute (opset 10+)
        attrs["dilations"] = dil
    if kind == "max":
        return g.node("MaxPool", ins, **attrs)
    # sum pool: AveragePool with zero-padding counted, times window size
    avg = g.node("AveragePool", ins, count_include_pad=1, **attrs)
    scale = g.init(np.asarray(float(np.prod(kernel)),
                              _np_dtype_of(eqn.invars[0])), "winsize")
    return g.node("Mul", [avg, scale])


def _np_dtype_of(var):
    return np.dtype(var.aval.dtype)


def _broadcast_in_dim(g, eqn, ins):
    p = eqn.params
    shape = [int(s) for s in p["shape"]]
    bdims = list(p["broadcast_dimensions"])
    in_shape = list(eqn.invars[0].aval.shape)
    interim = [1] * len(shape)
    for src, dst in enumerate(bdims):
        interim[dst] = in_shape[src]
    x = ins[0]
    if interim != in_shape:
        x = g.node("Reshape",
                   [x, g.init(np.asarray(interim, np.int64), "shape")])
    if interim != shape:
        x = g.node("Expand",
                   [x, g.init(np.asarray(shape, np.int64), "shape")])
    elif interim == in_shape:
        x = g.node("Identity", [x])
    return x


def _reshapeish(g, eqn, ins, new_shape):
    return g.node(
        "Reshape",
        [ins[0], g.init(np.asarray([int(s) for s in new_shape], np.int64),
                        "shape")])


def _gather(g, eqn, ins):
    """Simple take-along-one-axis gathers only (embedding lookups, x[idx])."""
    p = eqn.params
    dn = p["dimension_numbers"]
    operand = eqn.invars[0].aval
    slice_sizes = list(p["slice_sizes"])
    start_map = list(dn.start_index_map)
    collapsed = list(dn.collapsed_slice_dims)
    if len(start_map) == 1 and collapsed == start_map and \
            slice_sizes[start_map[0]] == 1 and \
            all(slice_sizes[d] == operand.shape[d]
                for d in range(len(slice_sizes)) if d != start_map[0]) and \
            not getattr(dn, "operand_batching_dims", ()):
        axis = start_map[0]
        idx = ins[1]
        # jax indices carry a trailing unit coordinate dim; drop it
        idx_shape = list(eqn.invars[1].aval.shape)
        if idx_shape and idx_shape[-1] == 1:
            idx = g.node("Reshape",
                         [idx, g.init(np.asarray(idx_shape[:-1] or [1],
                                                 np.int64), "shape")])
        out = g.node("Gather", [ins[0], idx], axis=axis)
        out_shape = [int(s) for s in eqn.outvars[0].aval.shape]
        return g.node("Reshape",
                      [out, g.init(np.asarray(out_shape, np.int64), "shape")])
    raise NotImplementedError(
        "ONNX export: general lax.gather (only single-axis take/embedding "
        "patterns are supported)")


class Converter:
    def __init__(self, opset: int = 13):
        if not 13 <= opset <= 17:
            raise NotImplementedError(
                f"ONNX export emits opset 13-17 op forms (ReduceSum/Slice "
                f"take tensor inputs; ReduceMax/Min/Prod still use the axes "
                f"attribute, removed in opset 18); opset_version={opset} "
                f"would produce an invalid model")
        self.pb = _pb.get()
        self.opset = opset

    # -- public --
    def convert(self, closed_jaxpr, input_names, graph_name="paddle_tpu"):
        pb = self.pb
        g = _Graph(pb, self.opset)
        jaxpr = closed_jaxpr.jaxpr
        env = {}

        for name, var in zip(input_names, jaxpr.invars):
            env[var] = name
        for var, const in zip(jaxpr.constvars, closed_jaxpr.consts):
            env[var] = g.init(np.asarray(const), "param")

        self._convert_eqns(g, jaxpr.eqns, env)

        graph = pb.GraphProto()
        graph.name = graph_name
        for name, var in zip(input_names, jaxpr.invars):
            graph.input.append(self._value_info(name, var.aval))
        out_names = []
        for i, var in enumerate(jaxpr.outvars):
            src = self._read(g, env, var)
            out = f"output_{i}"
            g.node("Identity", [src], out_names=[out])
            graph.output.append(self._value_info(out, var.aval))
            out_names.append(out)
        graph.node.extend(g.nodes)
        graph.initializer.extend(g.initializers.values())

        model = pb.ModelProto()
        model.ir_version = 8
        model.producer_name = "paddle_tpu"
        op = model.opset_import.add()
        op.domain = ""
        op.version = self.opset
        model.graph.CopyFrom(graph)
        return model

    # -- internals --
    def _value_info(self, name, aval):
        vi = self.pb.ValueInfoProto()
        vi.name = name
        tt = vi.type.tensor_type
        tt.elem_type = _onnx_dtype(aval.dtype)
        for s in aval.shape:
            tt.shape.dim.add().dim_value = int(s)
        return vi

    def _read(self, g, env, var):
        from jax._src.core import Literal

        if isinstance(var, Literal):
            return g.init(np.asarray(var.val), "lit")
        return env[var]

    def _convert_eqns(self, g, eqns, env):
        for eqn in eqns:
            prim = eqn.primitive.name
            ins = [self._read(g, env, v) for v in eqn.invars]
            outs = self._emit(g, eqn, prim, ins, env)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            for var, name in zip(eqn.outvars, outs):
                env[var] = name

    def _inline(self, g, eqn, ins, env, closed=None, open_jaxpr=None,
                consts=()):
        sub_env = {}
        jaxpr = closed.jaxpr if closed is not None else open_jaxpr
        sub_consts = closed.consts if closed is not None else consts
        for var, const in zip(jaxpr.constvars, sub_consts):
            sub_env[var] = g.init(np.asarray(const), "param")
        for var, name in zip(jaxpr.invars, ins):
            sub_env[var] = name
        self._convert_eqns(g, jaxpr.eqns, sub_env)
        return [self._read(g, sub_env, v) for v in jaxpr.outvars]

    def _emit(self, g, eqn, prim, ins, env):
        p = eqn.params
        pb = self.pb

        # --- structural / call primitives ---
        if prim in ("jit", "pjit", "closed_call", "core_call",
                    "custom_vjp_call", "custom_jvp_call", "remat",
                    "checkpoint", "custom_vjp_call_jaxpr", "remat2"):
            closed = p.get("jaxpr") or p.get("call_jaxpr") or \
                p.get("fun_jaxpr")
            if closed is None:
                raise NotImplementedError(f"ONNX export: {prim} w/o jaxpr")
            if hasattr(closed, "consts"):
                return self._inline(g, eqn, ins, env, closed=closed)
            return self._inline(g, eqn, ins, env, open_jaxpr=closed)

        if prim in _ELEMENTWISE:
            return g.node(_ELEMENTWISE[prim], ins)
        if prim in _COMPARE:
            return g.node(_COMPARE[prim], ins)
        if prim == "ne":
            return g.node("Not", [g.node("Equal", ins)])
        if prim == "erfc":
            one = g.init(np.asarray(1, _np_dtype_of(eqn.invars[0])), "one")
            return g.node("Sub", [one, g.node("Erf", ins)])
        if prim == "rsqrt":
            return g.node("Reciprocal", [g.node("Sqrt", ins)])
        if prim == "log1p":
            one = g.init(np.asarray(1, _np_dtype_of(eqn.invars[0])), "one")
            return g.node("Log", [g.node("Add", [ins[0], one])])
        if prim == "expm1":
            one = g.init(np.asarray(1, _np_dtype_of(eqn.invars[0])), "one")
            return g.node("Sub", [g.node("Exp", ins), one])
        if prim == "integer_pow":
            expo = g.init(np.asarray(p["y"], _np_dtype_of(eqn.invars[0])),
                          "expo")
            return g.node("Pow", [ins[0], expo])
        if prim == "square":
            return g.node("Mul", [ins[0], ins[0]])
        if prim == "rem":
            return g.node("Mod", ins, fmod=1)
        if prim in ("stop_gradient", "copy", "device_put", "convert_layout"):
            return g.node("Identity", [ins[0]])
        if prim == "convert_element_type":
            return g.node("Cast", [ins[0]],
                          to=_onnx_dtype(np.dtype(p["new_dtype"])))
        if prim == "select_n":
            if len(ins) != 3:
                raise NotImplementedError("ONNX export: select_n with >2 cases")
            return g.node("Where", [ins[0], ins[2], ins[1]])
        if prim == "clamp":
            # jax clamp(min, x, max); general broadcast via Max/Min pair
            return g.node("Min", [g.node("Max", [ins[1], ins[0]]), ins[2]])
        if prim == "transpose":
            return g.node("Transpose", [ins[0]],
                          perm=list(p["permutation"]))
        if prim == "reshape":
            return _reshapeish(g, eqn, ins, eqn.outvars[0].aval.shape)
        if prim == "squeeze":
            return _reshapeish(g, eqn, ins, eqn.outvars[0].aval.shape)
        if prim == "expand_dims":
            return _reshapeish(g, eqn, ins, eqn.outvars[0].aval.shape)
        if prim == "broadcast_in_dim":
            return _broadcast_in_dim(g, eqn, ins)
        if prim == "concatenate":
            return g.node("Concat", ins, axis=int(p["dimension"]))
        if prim == "slice":
            if p.get("strides") is None:
                strides = [1] * len(p["start_indices"])
            else:
                strides = list(p["strides"])
            n = len(p["start_indices"])
            return g.node(
                "Slice",
                [ins[0],
                 g.init(np.asarray(p["start_indices"], np.int64), "starts"),
                 g.init(np.asarray(p["limit_indices"], np.int64), "ends"),
                 g.init(np.asarray(range(n), np.int64), "axes"),
                 g.init(np.asarray(strides, np.int64), "steps")])
        if prim == "rev":
            dims = list(p["dimensions"])
            n = len(dims)
            return g.node(
                "Slice",
                [ins[0],
                 g.init(np.full(n, -1, np.int64), "starts"),
                 g.init(np.full(n, np.iinfo(np.int64).min, np.int64), "ends"),
                 g.init(np.asarray(dims, np.int64), "axes"),
                 g.init(np.full(n, -1, np.int64), "steps")])
        if prim == "pad":
            cfg = p["padding_config"]
            if any(i != 0 for _, _, i in cfg):
                raise NotImplementedError("ONNX export: interior padding")
            if any(lo < 0 or hi < 0 for lo, hi, _ in cfg):
                raise NotImplementedError("ONNX export: negative padding")
            pads = [int(lo) for lo, _, _ in cfg] + \
                   [int(hi) for _, hi, _ in cfg]
            return g.node(
                "Pad",
                [ins[0], g.init(np.asarray(pads, np.int64), "pads"), ins[1]])
        if prim == "iota":
            dt = np.dtype(p["dtype"])
            shape = tuple(int(s) for s in p["shape"])
            dim = int(p["dimension"])
            idx = np.arange(shape[dim], dtype=dt)
            arr = np.broadcast_to(
                idx.reshape([-1 if i == dim else 1
                             for i in range(len(shape))]), shape)
            return g.node("Identity", [g.init(np.ascontiguousarray(arr),
                                              "iota")])
        if prim == "reduce_sum":
            return g.node(
                "ReduceSum",
                [ins[0], g.init(np.asarray(p["axes"], np.int64), "axes")],
                keepdims=0)
        if prim in _REDUCE_ATTR:
            return g.node(_REDUCE_ATTR[prim], ins,
                          axes=list(p["axes"]), keepdims=0)
        if prim in ("reduce_and", "reduce_or"):
            x = g.node("Cast", [ins[0]], to=2)  # uint8
            red = "ReduceMin" if prim == "reduce_and" else "ReduceMax"
            x = g.node(red, [x], axes=list(p["axes"]), keepdims=0)
            return g.node("Cast", [x], to=9)
        if prim in ("argmax", "argmin"):
            axes = p["axes"]
            if len(axes) != 1:
                raise NotImplementedError("ONNX export: multi-axis argmax")
            op = "ArgMax" if prim == "argmax" else "ArgMin"
            out = g.node(op, ins, axis=int(axes[0]), keepdims=0)
            want = _onnx_dtype(np.dtype(p["index_dtype"]))
            if want != 7:
                out = g.node("Cast", [out], to=want)
            return out
        if prim == "cumsum":
            axis = g.init(np.asarray(p["axis"], np.int64), "axis")
            return g.node("CumSum", [ins[0], axis],
                          reverse=1 if p.get("reverse") else 0)
        if prim == "reduce_window_max":
            return _pool(g, eqn, ins, "max")
        if prim == "reduce_window_sum":
            return _pool(g, eqn, ins, "sum")
        if prim == "conv_general_dilated":
            return _conv(g, eqn, ins)
        if prim == "dot_general":
            return _dot_general(g, eqn, ins)
        if prim == "gather":
            return _gather(g, eqn, ins)
        if prim == "is_finite":
            inf = g.node("IsInf", [ins[0]])
            nan = g.node("IsNaN", [ins[0]])
            return g.node("Not", [g.node("Or", [inf, nan])])
        if prim == "sort":
            raise NotImplementedError(
                "ONNX export: lax.sort (use topk-based ops)")
        raise NotImplementedError(
            f"ONNX export: jaxpr primitive {prim!r} has no ONNX mapping yet")
