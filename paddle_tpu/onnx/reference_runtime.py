"""Dependency-free numpy evaluator for the ONNX subset this exporter emits.

Serves two purposes: round-trip verification in tests (export -> parse ->
execute -> compare against the live model) and a fallback runtime for
environments without onnxruntime (the ONNX project ships an analogous
reference evaluator).  Only the ops produced by converter.py are covered.
"""
from __future__ import annotations

import numpy as np

from . import _pb

_NP_DTYPE = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16,
             5: np.int16, 6: np.int32, 7: np.int64, 9: np.bool_,
             10: np.float16, 11: np.float64, 12: np.uint32, 13: np.uint64}


def _to_numpy(t):
    if t.data_type == 16:  # bfloat16: widen via uint16 bit pattern
        raw = np.frombuffer(t.raw_data, dtype=np.uint16)
        f32 = (raw.astype(np.uint32) << 16).view(np.float32)
        return f32.reshape(tuple(t.dims))
    dt = _NP_DTYPE[t.data_type]
    if t.raw_data:
        return np.frombuffer(t.raw_data, dtype=dt).reshape(tuple(t.dims))
    if t.data_type == 1:
        return np.asarray(t.float_data, dt).reshape(tuple(t.dims))
    if t.data_type == 7:
        return np.asarray(t.int64_data, dt).reshape(tuple(t.dims))
    return np.asarray(t.int32_data, dt).reshape(tuple(t.dims))


def _attrs(node):
    pb = _pb.get()
    out = {}
    for a in node.attribute:
        if a.type == pb.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == pb.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == pb.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == pb.AttributeProto.FLOATS:
            out[a.name] = list(a.floats)
        elif a.type == pb.AttributeProto.INTS:
            out[a.name] = list(a.ints)
        elif a.type == pb.AttributeProto.TENSOR:
            out[a.name] = _to_numpy(a.t)
    return out


def _pool_patches(x, kernel, strides, pads, pad_value=0, dilations=None):
    # x: [N, C, *spatial]; returns windows [N, C, *out_spatial, *kernel].
    # dilations: window dilation — elements d apart within each window
    # (ONNX MaxPool dilations / opset-19 AveragePool dilations).
    nsp = len(kernel)
    dil = list(dilations) if dilations else [1] * nsp
    k_eff = [(kernel[i] - 1) * dil[i] + 1 for i in range(nsp)]
    pad_width = [(0, 0), (0, 0)] + [
        (pads[i], pads[i + nsp]) for i in range(nsp)]
    xp = np.pad(x, pad_width, constant_values=pad_value)
    out_sp = [(xp.shape[2 + i] - k_eff[i]) // strides[i] + 1
              for i in range(nsp)]
    windows = np.empty(list(x.shape[:2]) + out_sp + list(kernel), x.dtype)
    for idx in np.ndindex(*out_sp):
        slc = tuple(slice(idx[i] * strides[i],
                          idx[i] * strides[i] + k_eff[i], dil[i])
                    for i in range(nsp))
        windows[(slice(None), slice(None)) + idx] = xp[(slice(None),
                                                        slice(None)) + slc]
    return windows, nsp


def _conv(x, w, attrs):
    strides = attrs.get("strides")
    pads = attrs.get("pads")
    dil = attrs.get("dilations")
    group = attrs.get("group", 1)
    kernel = list(w.shape[2:])
    nsp = len(kernel)
    # dilate kernel
    if any(d != 1 for d in dil):
        kd = [(k - 1) * d + 1 for k, d in zip(kernel, dil)]
        wd = np.zeros(list(w.shape[:2]) + kd, w.dtype)
        wd[(slice(None), slice(None))
           + tuple(slice(None, None, d) for d in dil)] = w
        w, kernel = wd, kd
    windows, _ = _pool_patches(x, kernel, strides, pads)
    # windows: [N, Cin, *out, *k]; w: [Cout, Cin/g, *k]
    N = x.shape[0]
    cout = w.shape[0]
    cin_g = w.shape[1]
    out_sp = windows.shape[2:2 + nsp]
    win = windows.reshape(N, group, cin_g, int(np.prod(out_sp)),
                          int(np.prod(kernel)))
    wg = w.reshape(group, cout // group, cin_g, int(np.prod(kernel)))
    out = np.einsum("ngcpk,gock->ngop", win, wg)
    return out.reshape((N, cout) + tuple(out_sp))


def run_model(model_bytes_or_proto, inputs):
    """Execute a serialized ModelProto on numpy inputs (dict or list)."""
    pb = _pb.get()
    if isinstance(model_bytes_or_proto, (bytes, bytearray)):
        model = pb.ModelProto()
        model.ParseFromString(bytes(model_bytes_or_proto))
    else:
        model = model_bytes_or_proto
    graph = model.graph
    env = {t.name: _to_numpy(t) for t in graph.initializer}
    input_names = [vi.name for vi in graph.input]
    if isinstance(inputs, dict):
        env.update({k: np.asarray(v) for k, v in inputs.items()})
    else:
        for name, v in zip(input_names, inputs):
            env[name] = np.asarray(v)

    for node in graph.node:
        op = node.op_type
        x = [env[n] for n in node.input]
        a = _attrs(node)
        if op == "Identity":
            y = x[0]
        elif op in ("Add", "Sub", "Mul", "Div", "Pow"):
            fn = {"Add": np.add, "Sub": np.subtract, "Mul": np.multiply,
                  "Div": np.divide, "Pow": np.power}[op]
            if op == "Div" and np.issubdtype(x[0].dtype, np.integer):
                y = x[0] // x[1]
            else:
                y = fn(x[0], x[1])
        elif op == "Max":
            y = np.maximum(x[0], x[1])
        elif op == "Min":
            y = np.minimum(x[0], x[1])
        elif op == "Mod":
            y = np.fmod(x[0], x[1]) if a.get("fmod") else np.mod(x[0], x[1])
        elif op in ("Exp", "Log", "Tanh", "Sqrt", "Abs", "Neg", "Sign",
                    "Floor", "Ceil", "Sin", "Cos", "Tan", "Asin", "Acos",
                    "Atan", "Sinh", "Cosh", "Reciprocal", "Not"):
            fn = {"Exp": np.exp, "Log": np.log, "Tanh": np.tanh,
                  "Sqrt": np.sqrt, "Abs": np.abs, "Neg": np.negative,
                  "Sign": np.sign, "Floor": np.floor, "Ceil": np.ceil,
                  "Sin": np.sin, "Cos": np.cos, "Tan": np.tan,
                  "Asin": np.arcsin, "Acos": np.arccos, "Atan": np.arctan,
                  "Sinh": np.sinh, "Cosh": np.cosh,
                  "Reciprocal": np.reciprocal,
                  "Not": np.logical_not}[op]
            y = fn(x[0])
        elif op == "Round":
            y = np.round(x[0])  # banker's rounding, matches ONNX
        elif op == "Erf":
            from math import erf
            y = np.vectorize(erf, otypes=[x[0].dtype])(x[0])
        elif op == "Sigmoid":
            y = 1.0 / (1.0 + np.exp(-x[0].astype(np.float64)))
            y = y.astype(x[0].dtype)
        elif op in ("And", "Or", "Xor"):
            fn = {"And": np.logical_and, "Or": np.logical_or,
                  "Xor": np.logical_xor}[op]
            y = fn(x[0], x[1])
        elif op in ("Equal", "Less", "LessOrEqual", "Greater",
                    "GreaterOrEqual"):
            fn = {"Equal": np.equal, "Less": np.less,
                  "LessOrEqual": np.less_equal, "Greater": np.greater,
                  "GreaterOrEqual": np.greater_equal}[op]
            y = fn(x[0], x[1])
        elif op == "Where":
            y = np.where(x[0], x[1], x[2])
        elif op == "Cast":
            y = x[0].astype(_NP_DTYPE[a["to"]])
        elif op == "Reshape":
            y = x[0].reshape(tuple(int(s) for s in x[1]))
        elif op == "Expand":
            y = np.broadcast_to(x[0], tuple(int(s) for s in x[1]))
        elif op == "Transpose":
            y = np.transpose(x[0], a["perm"])
        elif op == "Concat":
            y = np.concatenate(x, axis=a["axis"])
        elif op == "Slice":
            starts, ends, axes, steps = (x[1].tolist(), x[2].tolist(),
                                         x[3].tolist(), x[4].tolist())
            slc = [slice(None)] * x[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                e = None if (st < 0 and e < -x[0].shape[ax]) else e
                slc[ax] = slice(s, e, st)
            y = x[0][tuple(slc)]
        elif op == "Pad":
            pads = x[1].tolist()
            n = len(pads) // 2
            cval = x[2].item() if len(x) > 2 else 0
            y = np.pad(x[0], [(pads[i], pads[i + n]) for i in range(n)],
                       constant_values=cval)
        elif op == "ReduceSum":
            axes = tuple(x[1].tolist()) if len(x) > 1 else None
            y = np.sum(x[0], axis=axes, keepdims=bool(a.get("keepdims", 1)))
        elif op in ("ReduceMax", "ReduceMin", "ReduceProd"):
            fn = {"ReduceMax": np.max, "ReduceMin": np.min,
                  "ReduceProd": np.prod}[op]
            y = fn(x[0], axis=tuple(a["axes"]),
                   keepdims=bool(a.get("keepdims", 1)))
        elif op in ("ArgMax", "ArgMin"):
            fn = np.argmax if op == "ArgMax" else np.argmin
            y = fn(x[0], axis=a["axis"]).astype(np.int64)
            if a.get("keepdims", 1):
                y = np.expand_dims(y, a["axis"])
        elif op == "CumSum":
            y = x[0]
            ax = int(x[1])
            if a.get("reverse"):
                y = np.flip(np.cumsum(np.flip(y, ax), axis=ax), ax)
            else:
                y = np.cumsum(y, axis=ax)
            y = y.astype(x[0].dtype)
        elif op == "Einsum":
            y = np.einsum(a["equation"], *x)
        elif op == "Gather":
            y = np.take(x[0], x[1].astype(np.int64), axis=a.get("axis", 0))
        elif op == "MaxPool":
            neg = np.finfo(x[0].dtype).min \
                if np.issubdtype(x[0].dtype, np.floating) \
                else np.iinfo(x[0].dtype).min
            win, nsp = _pool_patches(x[0], a["kernel_shape"], a["strides"],
                                     a.get("pads", [0] * 2 * len(
                                         a["kernel_shape"])),
                                     pad_value=neg,  # ONNX pads with -inf
                                     dilations=a.get("dilations"))
            y = win.max(axis=tuple(range(-nsp, 0)))
        elif op == "AveragePool":
            win, nsp = _pool_patches(x[0], a["kernel_shape"], a["strides"],
                                     a.get("pads", [0] * 2 * len(
                                         a["kernel_shape"])),
                                     dilations=a.get("dilations"))
            y = win.mean(axis=tuple(range(-nsp, 0))).astype(x[0].dtype)
        elif op == "Conv":
            y = _conv(x[0], x[1], a)
        elif op == "IsInf":
            y = np.isinf(x[0])
        elif op == "IsNaN":
            y = np.isnan(x[0])
        else:
            raise NotImplementedError(f"reference runtime: op {op}")
        for out_name in node.output:
            env[out_name] = np.asarray(y)

    return [env[vi.name] for vi in graph.output]
