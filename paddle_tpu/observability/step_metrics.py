"""Step-level training telemetry: the :class:`StepTimer`.

``hapi.Model.fit`` drives batches through two alternating waits — the
host waiting on the DATA pipeline (``next(loader)``) and the host
waiting on the DEVICE (the blocking train step).  Which one dominates
decides whether a slow run needs input-pipeline work or kernel work, so
the timer splits them instead of reporting one opaque step time.

Usage (exactly how ``Model.fit`` wires it)::

    timer = StepTimer()
    for i, batch in timer.timed_enumerate(loader):   # data-wait measured
        loss = train_batch(batch)                    # device-wait
        timer.step(loss=loss, inputs=batch)

All metric NAMES are fixed constants with the wait recorded as a
``phase`` label — never interpolated into the name — which is the
bounded-cardinality discipline lint L006 enforces repo-wide.  Every
registry write is behind :func:`registry.enabled`, so an untelemetered
``fit`` pays only a few ``perf_counter`` calls per step.
"""
from __future__ import annotations

import time
from typing import Iterable, Iterator, Optional, Tuple

from . import registry as _registry

__all__ = ["StepTimer", "count_tokens"]


def count_tokens(inputs) -> int:
    """Token count of one batch: the element count of its first
    array-like (batch × seq_len for token models).  Unrecognizable
    structures count 0 — tokens/sec is best-effort, never a crash."""
    x = inputs
    while isinstance(x, (list, tuple)) and x:
        x = x[0]
    if isinstance(x, dict) and x:
        x = next(iter(x.values()))
    size = getattr(x, "size", None)
    if size is None:
        return 0
    try:
        return int(size() if callable(size) else size)
    except Exception:  # noqa: BLE001 — exotic array types
        return 0


class StepTimer:
    """Per-step wall-clock accounting with a data/device split.

    Python-side attributes (``steps``, ``tokens``, ``last_loss``,
    ``data_seconds``, ``device_seconds``, :meth:`steps_per_sec`,
    :meth:`tokens_per_sec`) are always live; the shared registry is
    mirrored only while :func:`registry.enabled`:

    - histogram ``train_step_seconds{phase=data|device|total}``
    - counters ``train_steps_total``, ``train_tokens_total``
    - gauges ``train_loss``, ``train_steps_per_sec``,
      ``train_tokens_per_sec``
    """

    def __init__(self, registry: Optional["_registry.MetricsRegistry"] = None):
        self._registry = registry
        self.steps = 0
        self.tokens = 0
        self.data_seconds = 0.0
        self.device_seconds = 0.0
        self.last_loss: Optional[float] = None
        self._started = time.perf_counter()
        self._mark = self._started        # end of the last accounted span
        self._last_data = 0.0             # data-wait of the current step
        self._handles = None              # (hist, counters, gauges) cache

    def _reg(self) -> "_registry.MetricsRegistry":
        return (self._registry if self._registry is not None
                else _registry.get_registry())

    # ------------------------------------------------------------ spans
    def timed_enumerate(self, iterable: Iterable) -> Iterator[Tuple[int, object]]:
        """``enumerate(iterable)`` with each ``next()``'s wall time
        recorded as that step's data-wait."""
        it = iter(iterable)
        i = 0
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            self._last_data = time.perf_counter() - t0
            yield i, batch
            i += 1

    def step(self, loss=None, inputs=None) -> None:
        """Close out one step: everything since the end of data-wait is
        device-wait.  Call after the train step's result is realized."""
        now = time.perf_counter()
        data = self._last_data
        device = max(0.0, now - self._mark - data)
        self._mark = now
        self._last_data = 0.0
        self.steps += 1
        self.data_seconds += data
        self.device_seconds += device
        ntok = count_tokens(inputs) if inputs is not None else 0
        self.tokens += ntok
        if loss is not None:
            try:
                self.last_loss = float(loss)
            except (TypeError, ValueError):
                pass
        if _registry.enabled():
            self._mirror(data, device, ntok)

    # ---------------------------------------------------------- derived
    def elapsed(self) -> float:
        return time.perf_counter() - self._started

    def steps_per_sec(self) -> float:
        dt = self.elapsed()
        return self.steps / dt if dt > 0 else 0.0

    def tokens_per_sec(self) -> float:
        dt = self.elapsed()
        return self.tokens / dt if dt > 0 else 0.0

    def summary(self) -> dict:
        busy = self.data_seconds + self.device_seconds
        return {
            "steps": self.steps,
            "tokens": self.tokens,
            "steps_per_sec": self.steps_per_sec(),
            "tokens_per_sec": self.tokens_per_sec(),
            "data_seconds": self.data_seconds,
            "device_seconds": self.device_seconds,
            "data_fraction": self.data_seconds / busy if busy > 0 else 0.0,
            "last_loss": self.last_loss,
        }

    # ----------------------------------------------------------- mirror
    def _mirror(self, data: float, device: float, ntok: int) -> None:
        # handles are resolved once per timer (one fit() call), keeping
        # the per-step cost to the observations themselves
        if self._handles is None:
            reg = self._reg()
            self._handles = (
                reg.histogram("train_step_seconds",
                              "per-step wall time by wait phase"),
                reg.counter("train_steps_total", "train steps completed"),
                reg.counter("train_tokens_total",
                            "tokens consumed by training"),
                reg.gauge("train_loss", "last observed training loss"),
                reg.gauge("train_steps_per_sec",
                          "training throughput (steps/s, run average)"),
                reg.gauge("train_tokens_per_sec",
                          "training throughput (tokens/s, run average)"),
            )
        hist, c_steps, c_tok, g_loss, g_sps, g_tps = self._handles
        hist.observe(data, phase="data")
        hist.observe(device, phase="device")
        hist.observe(data + device, phase="total")
        c_steps.inc()
        if ntok:
            c_tok.inc(ntok)
        if self.last_loss is not None:
            g_loss.set(self.last_loss)
        g_sps.set(self.steps_per_sec())
        g_tps.set(self.tokens_per_sec())
