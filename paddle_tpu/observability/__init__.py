"""paddle_tpu.observability — unified telemetry for the framework.

One process-global :class:`MetricsRegistry` is the single pane of glass
over every producer in the repo:

- ``hapi.Model.fit`` (via :class:`StepTimer`: steps/sec, tokens/sec,
  data-wait vs device-wait, loss);
- the serving engine (TTFT/TPOT/occupancy/preemptions mirrored from
  ``serving.metrics``);
- resilience (checkpoint save latency, corrupt checkpoints skipped);
- any jit entry point wrapped with :func:`track_compiles` /
  :func:`warn_on_retrace` (runtime compile and retrace accounting —
  the dynamic half of the H101 hazard).

Telemetry is OFF by default: every producer call sites checks
:func:`enabled` first, so an untelemetered run pays ~nothing.  Turning
it on is one line — ``FileSink(dir).start()`` (periodic Prometheus +
JSON dumps), or :func:`enable` plus an explicit
:func:`prometheus_text` / :func:`to_json` export.

Pure stdlib; importable from anywhere in the framework without cycles.
"""
from .registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricSnapshot,
    MetricsRegistry,
    collect,
    disable,
    enable,
    enabled,
    get_registry,
)
from .exporters import (  # noqa: F401
    FileSink,
    prometheus_text,
    to_json,
    write_json,
    write_prometheus,
)
from .compile_tracker import (  # noqa: F401
    RetraceError,
    RetraceWarning,
    TrackedFunction,
    compile_stats,
    jit_cache_size,
    track_compiles,
    warn_on_retrace,
)
from .step_metrics import StepTimer, count_tokens  # noqa: F401

__all__ = [
    # registry
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram", "MetricSnapshot",
    "MetricsRegistry", "collect", "disable", "enable", "enabled",
    "get_registry",
    # exporters
    "FileSink", "prometheus_text", "to_json", "write_json",
    "write_prometheus",
    # compile tracking
    "RetraceError", "RetraceWarning", "TrackedFunction", "compile_stats",
    "jit_cache_size", "track_compiles", "warn_on_retrace",
    # step metrics
    "StepTimer", "count_tokens",
]
