"""Exporters over :func:`registry.collect` snapshots.

Three consumers, one snapshot format:

- :func:`prometheus_text` — the Prometheus text exposition format
  (``# HELP``/``# TYPE`` headers, ``_bucket{le=...}``/``_sum``/
  ``_count`` histogram triplets), scrape-ready;
- :func:`to_json` / :func:`write_json` — structured JSON for log
  pipelines and the CI assertions in ``examples/observe_train.py``;
- :class:`FileSink` — a periodic background writer dumping both formats
  to a directory (atomic ``os.replace`` so a scraper never reads a torn
  file); ``start()`` also flips the global :func:`registry.enable`
  switch, which is what arms the framework's producers.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .registry import MetricsRegistry, MetricSnapshot, enable, get_registry

__all__ = ["prometheus_text", "to_json", "write_json",
           "write_prometheus", "FileSink"]


def _escape_label_value(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _label_str(key, extra: Optional[List] = None) -> str:
    pairs = list(key) + list(extra or [])
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(str(v))}"'
                    for k, v in pairs)
    return "{" + body + "}"


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """One snapshot in the Prometheus text exposition format (0.0.4).
    Histogram buckets are emitted CUMULATIVE with an ``+Inf`` terminal
    bucket equal to ``_count``, per the format spec."""
    reg = registry if registry is not None else get_registry()
    lines: List[str] = []
    for snap in reg.collect():
        if snap.help:
            lines.append(f"# HELP {snap.name} {snap.help}")
        lines.append(f"# TYPE {snap.name} {snap.kind}")
        for key in sorted(snap.series):
            val = snap.series[key]
            if snap.kind == "histogram":
                cum = 0
                for bound, n in zip(snap.boundaries, val["buckets"]):
                    cum += n
                    lines.append(
                        f"{snap.name}_bucket"
                        f"{_label_str(key, [('le', _fmt(bound))])} {cum}")
                lines.append(
                    f"{snap.name}_bucket"
                    f"{_label_str(key, [('le', '+Inf')])} {val['count']}")
                lines.append(f"{snap.name}_sum{_label_str(key)} "
                             f"{repr(float(val['sum']))}")
                lines.append(f"{snap.name}_count{_label_str(key)} "
                             f"{val['count']}")
            else:
                lines.append(f"{snap.name}{_label_str(key)} {_fmt(val)}")
    return "\n".join(lines) + ("\n" if lines else "")


def _snap_to_json(snap: MetricSnapshot) -> dict:
    series = []
    for key in sorted(snap.series):
        val = snap.series[key]
        entry: dict = {"labels": dict(key)}
        if snap.kind == "histogram":
            entry.update({"buckets": list(val["buckets"]),
                          "sum": float(val["sum"]),
                          "count": int(val["count"])})
        else:
            entry["value"] = float(val)
        series.append(entry)
    out = {"name": snap.name, "kind": snap.kind, "help": snap.help,
           "series": series}
    if snap.boundaries is not None:
        out["boundaries"] = list(snap.boundaries)
    return out


def to_json(registry: Optional[MetricsRegistry] = None) -> dict:
    """One snapshot as a JSON-ready dict:
    ``{"ts": unix_seconds, "metrics": [...]}``."""
    reg = registry if registry is not None else get_registry()
    return {"ts": time.time(),
            "metrics": [_snap_to_json(s) for s in reg.collect()]}


def _atomic_write(path: str, data: str):
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(data)
    os.replace(tmp, path)


def write_json(path: str, registry: Optional[MetricsRegistry] = None) -> str:
    """Dump :func:`to_json` to ``path`` (atomic replace); returns path."""
    _atomic_write(path, json.dumps(to_json(registry), indent=1))
    return path


def write_prometheus(path: str,
                     registry: Optional[MetricsRegistry] = None) -> str:
    """Dump :func:`prometheus_text` to ``path`` (atomic replace)."""
    _atomic_write(path, prometheus_text(registry))
    return path


class FileSink:
    """Periodic metrics dumper: every ``interval_s`` (and on ``stop()``)
    writes ``<prefix>.prom`` and ``<prefix>.json`` into ``directory``.

    Installing the sink is what turns the framework's telemetry ON:
    ``start()`` calls :func:`registry.enable` (and ``stop()`` restores
    the previous state), so code paths stay no-op until someone actually
    wants the numbers.  ``interval_s=None`` skips the thread — use
    :meth:`dump` for explicit one-shot exports.
    """

    def __init__(self, directory: str, interval_s: Optional[float] = 10.0,
                 prefix: str = "metrics",
                 registry: Optional[MetricsRegistry] = None):
        if interval_s is not None and interval_s <= 0:
            raise ValueError("interval_s must be positive (or None)")
        self.directory = directory
        self.interval_s = interval_s
        self.prefix = prefix
        self._registry = registry
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._prev_enabled: Optional[bool] = None
        self.writes = 0

    # -- paths
    @property
    def prom_path(self) -> str:
        return os.path.join(self.directory, f"{self.prefix}.prom")

    @property
    def json_path(self) -> str:
        return os.path.join(self.directory, f"{self.prefix}.json")

    def dump(self) -> Dict[str, str]:
        """Write both formats once; returns ``{"prom": ..., "json": ...}``."""
        os.makedirs(self.directory, exist_ok=True)
        out = {"prom": write_prometheus(self.prom_path, self._registry),
               "json": write_json(self.json_path, self._registry)}
        self.writes += 1
        return out

    # -- lifecycle
    def start(self) -> "FileSink":
        self._prev_enabled = enable(True)
        if self.interval_s is not None and self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="observability-sink", daemon=True)
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.dump()
            except Exception:  # noqa: BLE001 — a full disk must not kill it
                pass

    def stop(self, final_dump: bool = True):
        if self._thread is not None:
            self._stop.set()
            self._thread.join()
            self._thread = None
        if final_dump:
            self.dump()
        if self._prev_enabled is not None:
            enable(self._prev_enabled)
            self._prev_enabled = None

    def __enter__(self) -> "FileSink":
        return self.start()

    def __exit__(self, *a):
        self.stop()
        return False
