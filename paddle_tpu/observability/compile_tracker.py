"""Runtime XLA compile/retrace accounting.

The H101 hazard detector (``paddle_tpu.analysis``) can say a function
*might* retrace; it cannot measure how often it actually does.  PAPERS.md
("Operator Fusion in XLA: Analysis and Evaluation") shows compile-time
behavior dominating real TPU performance while staying invisible without
dedicated accounting — this module is that accounting:

- :func:`track_compiles` wraps a jit entry point (``jax.jit`` product or
  ``jit.to_static``'s StaticFunction) and records, per function: compile
  count, cumulative compile seconds, and live jit-cache size.  A compile
  is detected as jit-cache growth across a call, and that call's wall
  time is attributed to compilation (trace+lower+compile dominates any
  call that grows the cache).
- :func:`warn_on_retrace` is the reusable no-retrace guard: it allows
  ``after`` compiles (warmup), then every further compile — a RETRACE —
  warns (:class:`RetraceWarning`) or raises (:class:`RetraceError`).
  The serving engine's strict no-retrace assertion is this primitive
  with ``on_retrace="raise"``.
- :func:`compile_stats` aggregates every live tracked function;
  when :func:`registry.enabled`, each compile also lands in the shared
  registry (``xla_compiles_total`` / ``xla_compile_seconds_total``
  counters and the ``xla_jit_cache_entries`` gauge, labeled by ``fn``).
"""
from __future__ import annotations

import functools
import threading
import time
import warnings
import weakref
from typing import Callable, Dict, List, Optional

from . import registry as _registry

__all__ = [
    "RetraceError",
    "RetraceWarning",
    "TrackedFunction",
    "track_compiles",
    "warn_on_retrace",
    "jit_cache_size",
    "compile_stats",
]


class RetraceError(RuntimeError):
    """A guarded function retraced past its warmup allowance."""


class RetraceWarning(UserWarning):
    """A guarded function retraced past its warmup allowance."""


def jit_cache_size(fn) -> int:
    """Live jit-cache entries behind ``fn``: a ``jax.jit`` product
    (``_cache_size()``), a ``jit.to_static`` StaticFunction (its
    input-spec cache), or an already-tracked function (delegates)."""
    probe = getattr(fn, "_cache_size", None)
    if callable(probe):                      # jax.jit / TrackedFunction
        return int(probe())
    cache = getattr(fn, "_cache", None)
    if isinstance(cache, dict):              # jit.to_static StaticFunction
        return len(cache)
    raise TypeError(
        f"cannot read a jit cache from {type(fn).__name__} — expected a "
        "jax.jit-compiled function, a jit.to_static StaticFunction, or "
        "a TrackedFunction")


# live tracked functions, for compile_stats(); weak so tracking never
# extends a model's lifetime (decode steps capture whole models)
_tracked: List["weakref.ref[TrackedFunction]"] = []
_tracked_lock = threading.Lock()


class TrackedFunction:
    """Transparent wrapper recording compile events of a jit entry point.

    ``compiles``/``compile_seconds`` count cache-growth calls and their
    wall time; ``calls`` counts everything.  The wrapped function's
    attributes (``__name__``, ``_cache_size``) stay reachable, so a
    TrackedFunction drops in anywhere the raw jitted callable went.
    """

    def __init__(self, fn: Callable, label: Optional[str] = None):
        jit_cache_size(fn)                   # fail fast on untrackable fns
        self._fn = fn
        self.label = label or getattr(fn, "__name__", None) or repr(fn)
        self.calls = 0
        self.compiles = 0
        self.compile_seconds = 0.0
        functools.update_wrapper(self, fn, updated=[])
        with _tracked_lock:
            _tracked.append(weakref.ref(self))

    # the engine and tests read cache sizes through the wrapper
    def cache_size(self) -> int:
        return jit_cache_size(self._fn)

    _cache_size = cache_size

    def __call__(self, *args, **kwargs):
        before = jit_cache_size(self._fn)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        self.calls += 1
        after = jit_cache_size(self._fn)
        if after > before:
            dt = time.perf_counter() - t0
            self.compiles += after - before
            self.compile_seconds += dt
            self._on_compile(after, dt)
        return out

    def _on_compile(self, cache_size: int, dt: float):
        if _registry.enabled():
            _mirror_compile(self.label, cache_size, dt)

    def stats(self) -> dict:
        return {"label": self.label, "calls": self.calls,
                "compiles": self.compiles,
                "compile_seconds": self.compile_seconds,
                "cache_size": self.cache_size()}

    def __repr__(self):
        return (f"<TrackedFunction {self.label!r} compiles={self.compiles} "
                f"cache={self.cache_size()}>")


def _mirror_compile(label: str, cache_size: int, dt: float):
    """Land one compile event in the shared registry (enabled() only)."""
    reg = _registry.get_registry()
    reg.counter("xla_compiles_total",
                "jit compiles observed per tracked entry point").inc(
                    fn=label)
    reg.counter("xla_compile_seconds_total",
                "cumulative wall seconds of compiling calls").inc(
                    dt, fn=label)
    reg.gauge("xla_jit_cache_entries",
              "live jit-cache entries per tracked entry point").set(
                  cache_size, fn=label)


class _RetraceGuarded(TrackedFunction):
    """TrackedFunction that reacts once ``compiles`` exceeds ``after``."""

    def __init__(self, fn: Callable, after: int = 1,
                 label: Optional[str] = None, on_retrace: str = "warn"):
        if after < 0:
            raise ValueError("after must be >= 0")
        if on_retrace not in ("warn", "raise", "count"):
            raise ValueError("on_retrace must be 'warn', 'raise' or "
                             "'count'")
        super().__init__(fn, label=label)
        self.after = after
        self.on_retrace = on_retrace

    @property
    def retraces(self) -> int:
        """Compiles past the warmup allowance."""
        return max(0, self.compiles - self.after)

    def _on_compile(self, cache_size: int, dt: float):
        super()._on_compile(cache_size, dt)
        if self.compiles <= self.after:
            return
        if _registry.enabled():
            _registry.get_registry().counter(
                "xla_retraces_total",
                "compiles past the warmup allowance (H101 at runtime)",
            ).inc(fn=self.label)
        msg = (f"{self.label}: retraced after warmup (compile "
               f"#{self.compiles}, allowance {self.after}; jit cache now "
               f"{cache_size} entries) — an input changed shape/dtype; "
               "on TPU this recompiles per call (H101)")
        if self.on_retrace == "raise":
            raise RetraceError(msg)
        if self.on_retrace == "warn":
            warnings.warn(msg, RetraceWarning, stacklevel=4)


def track_compiles(fn: Optional[Callable] = None, *,
                   label: Optional[str] = None):
    """Wrap ``fn`` in a :class:`TrackedFunction`; usable bare or as a
    decorator (``@track_compiles`` / ``@track_compiles(label=...)``)."""
    if fn is None:
        return lambda f: TrackedFunction(f, label=label)
    return TrackedFunction(fn, label=label)


def warn_on_retrace(fn: Callable, after: int = 1,
                    label: Optional[str] = None,
                    on_retrace: str = "warn") -> _RetraceGuarded:
    """The reusable no-retrace guard: returns ``fn`` wrapped so that its
    first ``after`` compiles (warmup) pass silently and every compile
    beyond them triggers ``on_retrace`` — ``"warn"`` (default),
    ``"raise"`` (the serving engine's strict contract), or ``"count"``
    (record only; read ``.retraces``).  Compiles are detected as
    jit-cache growth, so functions whose executables are shared across
    wrappers (e.g. decode steps cached on a model) are counted by what
    THIS call path actually compiled."""
    return _RetraceGuarded(fn, after=after, label=label,
                           on_retrace=on_retrace)


def compile_stats() -> Dict[str, dict]:
    """Aggregated stats of every live tracked function, by label.
    Labels repeat (two engines tracking the same model's decode step):
    counts merge, cache_size takes the latest."""
    out: Dict[str, dict] = {}
    with _tracked_lock:
        live = [r() for r in _tracked]
        _tracked[:] = [r for r, t in zip(_tracked, live) if t is not None]
    for t in live:
        if t is None:
            continue
        s = t.stats()
        agg = out.setdefault(s["label"], {
            "calls": 0, "compiles": 0, "compile_seconds": 0.0,
            "cache_size": 0})
        agg["calls"] += s["calls"]
        agg["compiles"] += s["compiles"]
        agg["compile_seconds"] += s["compile_seconds"]
        agg["cache_size"] = s["cache_size"]
    return out
