"""Process-global metrics registry: Counter / Gauge / Histogram.

The single pane of glass the ROADMAP's production claim needs: every
subsystem (hapi training loop, serving engine, resilient checkpointer,
XLA compile tracker) reports through ONE registry, and one
snapshot-consistent :func:`MetricsRegistry.collect` feeds every exporter
(``observability.exporters``).

Design rules (each earned by a production failure mode):

- **Fixed metric names, labels for dimensions.**  A metric name built
  with an f-string (``Counter(f"requests_{user}")``) creates one series
  per distinct value — unbounded registry growth.  Lint L006
  (``analysis.astlint``) flags exactly that call-site shape; dynamic
  parts belong in labels.
- **Hard label-cardinality cap.**  Labels are bounded too: past
  ``max_series`` distinct label-sets, further observations fold into a
  reserved ``{"overflow": "true"}`` series (warned once) instead of
  growing without bound.
- **Snapshot-consistent collect().**  One registry lock guards every
  mutation; ``collect()`` copies every series under that lock, so an
  exporter never sees a histogram whose ``sum`` and ``count`` disagree.
- **No-op when idle.**  Producers across the framework consult
  :func:`enabled` (a dict read) before touching the registry; until
  :func:`enable` is called — directly or by installing an exporter sink
  — the hot paths pay one boolean check and nothing else.
"""
from __future__ import annotations

import math
import re
import threading
import warnings
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricSnapshot",
    "DEFAULT_BUCKETS",
    "get_registry",
    "collect",
    "enable",
    "disable",
    "enabled",
]

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_LABEL_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")

# Latency-shaped fixed boundaries (seconds), Prometheus client defaults:
# fixed at metric creation so bucket counts stay comparable across the
# whole process lifetime (a run-time re-bucketing would corrupt rates).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# The reserved series every over-cap observation folds into.
_OVERFLOW_KEY: Tuple[Tuple[str, str], ...] = (("overflow", "true"),)


# ---------------------------------------------------------------------------
# global on/off switch (the idle fast path)
# ---------------------------------------------------------------------------

_STATE = {"enabled": False}


def enabled() -> bool:
    """Whether framework producers should record into the registry.
    Hot paths (Model.fit batches, serving decode iterations, checkpoint
    saves) check this one dict read and skip ALL metric work when off."""
    return _STATE["enabled"]


def enable(on: bool = True) -> bool:
    """Turn framework-wide metric production on (returns the previous
    state).  Installing an exporter sink (``FileSink.start``) calls this
    for you."""
    prev = _STATE["enabled"]
    _STATE["enabled"] = bool(on)
    return prev


def disable() -> bool:
    """``enable(False)``."""
    return enable(False)


# ---------------------------------------------------------------------------
# snapshots
# ---------------------------------------------------------------------------

class MetricSnapshot(NamedTuple):
    """One metric at one collect() instant.  ``series`` maps a sorted
    ``((label, value), ...)`` key to a float (counter/gauge) or to a
    ``{"buckets": [int, ...], "sum": float, "count": int}`` dict
    (histogram; ``buckets`` is cumulative-free per-bucket counts aligned
    with ``boundaries`` plus one final +Inf bucket)."""

    name: str
    kind: str
    help: str
    series: Dict[Tuple[Tuple[str, str], ...], object]
    boundaries: Optional[Tuple[float, ...]] = None


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 registry: Optional["MetricsRegistry"] = None,
                 max_series: int = 64):
        if not _NAME_RE.match(name or ""):
            raise ValueError(f"invalid metric name {name!r} (want "
                             "[a-zA-Z_:][a-zA-Z0-9_:]*)")
        if max_series < 1:
            raise ValueError("max_series must be >= 1")
        self.name = name
        self.help = help
        self.max_series = max_series
        self._series: Dict[Tuple[Tuple[str, str], ...], object] = {}
        self._overflowed = False
        registry = registry if registry is not None else get_registry()
        self._registry = registry
        self._lock = registry._lock          # shared: collect() is atomic
        registry._register(self)

    # -- series bookkeeping
    def _key(self, labels: Dict[str, object]
             ) -> Tuple[Tuple[str, str], ...]:
        if not labels:
            return ()
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name {k!r}")
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        if key not in self._series and len(self._series) >= self.max_series:
            if not self._overflowed:
                self._overflowed = True
                warnings.warn(
                    f"metric {self.name!r} exceeded its label-cardinality "
                    f"cap ({self.max_series} series); further new label "
                    "sets fold into the {'overflow': 'true'} series — "
                    "dynamic values belong in bounded labels (lint L006)",
                    RuntimeWarning, stacklevel=4)
            return _OVERFLOW_KEY
        return key

    def _zero(self):
        return 0.0

    def _cell(self, labels: Dict[str, object]):
        key = self._key(labels)
        cell = self._series.get(key)
        if cell is None:
            cell = self._series[key] = self._zero()
        return key, cell

    def labels_count(self) -> int:
        with self._lock:
            return len(self._series)

    def snapshot(self) -> MetricSnapshot:
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> MetricSnapshot:
        return MetricSnapshot(self.name, self.kind, self.help,
                              dict(self._series))


class Counter(_Metric):
    """Monotonic counter.  ``inc(value, **labels)``; negative increments
    are a ValueError (rates depend on monotonicity)."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels):
        if value < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease "
                             f"(inc({value}))")
        with self._lock:
            key, cur = self._cell(labels)
            self._series[key] = cur + value

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))


class Gauge(_Metric):
    """Point-in-time value: ``set``/``inc``/``dec``."""

    kind = "gauge"

    def set(self, value: float, **labels):
        with self._lock:
            key, _ = self._cell(labels)
            self._series[key] = float(value)

    def inc(self, value: float = 1.0, **labels):
        with self._lock:
            key, cur = self._cell(labels)
            self._series[key] = cur + value

    def dec(self, value: float = 1.0, **labels):
        self.inc(-value, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))


class Histogram(_Metric):
    """Fixed-boundary histogram.  Boundaries are upper bounds (``le``),
    ascending, fixed at creation; one implicit +Inf bucket is appended.
    Each series holds per-bucket counts plus ``sum``/``count``."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 registry: Optional["MetricsRegistry"] = None,
                 max_series: int = 64):
        bounds = tuple(float(b) for b in buckets)
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError(f"histogram buckets must be strictly "
                             f"ascending, got {bounds}")
        if math.isinf(bounds[-1]):
            bounds = bounds[:-1]            # +Inf is implicit
        self.boundaries = bounds
        super().__init__(name, help, registry=registry,
                         max_series=max_series)

    def _zero(self):
        return {"buckets": [0] * (len(self.boundaries) + 1),
                "sum": 0.0, "count": 0}

    def observe(self, value: float, **labels):
        v = float(value)
        with self._lock:
            _, cell = self._cell(labels)
            i = 0
            for i, bound in enumerate(self.boundaries):
                if v <= bound:
                    break
            else:
                i = len(self.boundaries)    # +Inf bucket
            cell["buckets"][i] += 1
            cell["sum"] += v
            cell["count"] += 1

    def count(self, **labels) -> int:
        with self._lock:
            cell = self._series.get(self._key(labels))
            return int(cell["count"]) if cell else 0

    def sum(self, **labels) -> float:
        with self._lock:
            cell = self._series.get(self._key(labels))
            return float(cell["sum"]) if cell else 0.0

    def _snapshot_locked(self) -> MetricSnapshot:
        series = {k: {"buckets": list(v["buckets"]), "sum": v["sum"],
                      "count": v["count"]}
                  for k, v in self._series.items()}
        return MetricSnapshot(self.name, self.kind, self.help, series,
                              self.boundaries)


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

class MetricsRegistry:
    """Name -> metric map with get-or-create accessors and an atomic
    :meth:`collect`.  One RLock guards registration, every metric
    mutation, and collection (metrics share the registry's lock), so a
    collect() is a consistent cut across all metrics."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    # -- registration
    def _register(self, metric: _Metric):
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                raise ValueError(
                    f"metric {metric.name!r} already registered as "
                    f"{existing.kind} — use registry."
                    f"{existing.kind}(...) to share it")
            self._metrics[metric.name] = metric

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise TypeError(
                        f"metric {name!r} is a {existing.kind}, not a "
                        f"{cls.kind}")
                return existing
            return cls(name, help, registry=self, **kwargs)

    def counter(self, name: str, help: str = "",
                max_series: int = 64) -> Counter:
        """Get-or-create a :class:`Counter`."""
        return self._get_or_create(Counter, name, help,
                                   max_series=max_series)

    def gauge(self, name: str, help: str = "",
              max_series: int = 64) -> Gauge:
        """Get-or-create a :class:`Gauge`."""
        return self._get_or_create(Gauge, name, help,
                                   max_series=max_series)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  max_series: int = 64) -> Histogram:
        """Get-or-create a :class:`Histogram`; re-requesting one with
        different boundaries is a ValueError (buckets are fixed)."""
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, Histogram):
                    raise TypeError(f"metric {name!r} is a "
                                    f"{existing.kind}, not a histogram")
                want = tuple(float(b) for b in buckets)
                if math.isinf(want[-1]) if want else False:
                    want = want[:-1]
                if want != existing.boundaries:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"boundaries {existing.boundaries}, requested "
                        f"{want} — buckets are fixed at creation")
                return existing
            return Histogram(name, help, buckets=buckets, registry=self,
                             max_series=max_series)

    # -- access
    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def unregister(self, name: str) -> bool:
        with self._lock:
            return self._metrics.pop(name, None) is not None

    def clear(self):
        """Drop every metric (tests; exporters of a cleared registry
        emit nothing)."""
        with self._lock:
            self._metrics.clear()

    def collect(self) -> List[MetricSnapshot]:
        """Atomic snapshot of every metric, name-sorted.  Taken under
        the shared lock: no concurrent inc()/observe() can land between
        two metrics' copies."""
        with self._lock:
            return [self._metrics[n]._snapshot_locked()
                    for n in sorted(self._metrics)]


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-global default registry (created on first use)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = MetricsRegistry()
    return _default


def collect() -> List[MetricSnapshot]:
    """``get_registry().collect()``."""
    return get_registry().collect()
