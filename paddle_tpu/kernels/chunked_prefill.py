"""Fused chunked-prefill attention kernel (Pallas) with an XLA fallback.

This is the first kernel MINED rather than hand-picked: on the fused
prefill trace, analysis/fusionminer ranks the chunked-prefill attention
inner loop as the #1 remaining candidate — the gathered [B, L, KVH, D]
context copy, the [B, H, T, L] score/probability tensors and the
repeat-to-H KV expansion all round-trip HBM between the two attention
matmuls, while only the projection epilogues around them fuse.

The kernel attends one query CHUNK (T tokens per sequence, already
RoPE-rotated and scattered into the pools by the caller) over each
sequence's paged KV context in one pass: the block table rides in as a
scalar-prefetch operand, each grid step DMAs exactly one KV block from
the pool, and an online (flash) softmax keeps the running max/sum and
accumulator for all T queries in VMEM.  GQA never materializes the
repeat: queries are grouped [B, KVH, rep*T, D] so every q row of a
group shares the group's KV block.

Numerics contract: ``_xla_chunked`` is the same grouped-query math in
plain XLA ops (identical masking, f32 accumulation, full softmax in
place of the online rescale).  On CPU the fused path lowers through
it, so tier-1 and the jaxpr audits cover the exact fused-step math
with no pallas_call in the program.  models/llama.py's ``_paged_attn``
gather path stays the unfused parity oracle.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from .costs import KernelCost, register_kernel_cost
from .kv_quant import decode_codes

KERNEL_NAME = "fused_chunked_prefill"
NEG_INF = -1e30


def _chunk_kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                  bs, chunk, n_pages, kv_dtype=None):
    if kv_dtype is not None:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # q rows are [rep * chunk, D] with row r * chunk + t; scale is
    # already folded into q by the caller, so the score math is a bare
    # dot against this page's gathered block.  Quantized pools dequant
    # right at the DMA boundary: the int8 block just landed in VMEM and
    # the per-row scale multiply rides the same f32 upcast.
    qv = q_ref[0, 0].astype(jnp.float32)                # [RT, D]
    if kv_dtype is not None:
        kb = decode_codes(k_ref[0, :, 0, :], kv_dtype) * \
            ks_ref[0][:, None]                          # [bs, D]
        vb = decode_codes(v_ref[0, :, 0, :], kv_dtype) * \
            vs_ref[0][:, None]
    else:
        kb = k_ref[0, :, 0, :].astype(jnp.float32)      # [bs, D]
        vb = v_ref[0, :, 0, :].astype(jnp.float32)

    scores = jax.lax.dot_general(
        qv, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)             # [RT, bs]

    # causal chunk mask: key position vs this row's query position
    # pos_ref[b] + t.  Page 0 always holds key position 0, so m stays
    # anchored to a real score and masked lanes underflow to exp(-inf).
    k_pos = p * bs + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    q_pos = pos_ref[b] + \
        jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0) % chunk
    scores = jnp.where(k_pos <= q_pos, scores, NEG_INF)

    m_cur = jnp.max(scores, axis=-1, keepdims=True)     # [RT, 1]
    m_new = jnp.maximum(m_ref[:], m_cur)
    alpha = jnp.exp(m_ref[:] - m_new)
    pexp = jnp.exp(scores - m_new)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        pexp, vb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # [RT, D]
    l_ref[:] = l_ref[:] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
    m_ref[:] = m_new

    @pl.when(p == n_pages - 1)
    def _emit():
        o_ref[0, 0] = (acc_ref[:] /
                       jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _pallas_chunked(q_g, k_pool, v_pool, block_table, positions, chunk,
                    interpret, k_scale=None, v_scale=None, kv_dtype=None):
    """q_g: grouped, ROTATED, pre-scaled [B, KVH, RT, D] f32 queries;
    returns the normalized context [B, KVH, RT, D] f32."""
    B, KVH, RT, D = q_g.shape
    bs = k_pool.shape[1]
    nbs = block_table.shape[1]

    in_specs = [
        pl.BlockSpec((1, 1, RT, D),
                     lambda b, h, p, bt, pos: (b, h, 0, 0)),
        pl.BlockSpec((1, bs, 1, D),
                     lambda b, h, p, bt, pos: (bt[b, p], 0, h, 0)),
        pl.BlockSpec((1, bs, 1, D),
                     lambda b, h, p, bt, pos: (bt[b, p], 0, h, 0)),
    ]
    operands = [q_g, k_pool, v_pool]
    if kv_dtype is not None:
        # per-row scale sidecars ride the same block-table indexing as
        # the pools they describe ([nb, bs] -> one (1, bs) row strip)
        in_specs += [
            pl.BlockSpec((1, bs), lambda b, h, p, bt, pos: (bt[b, p], 0)),
            pl.BlockSpec((1, bs), lambda b, h, p, bt, pos: (bt[b, p], 0)),
        ]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KVH, nbs),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, RT, D),
                               lambda b, h, p, bt, pos: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((RT, D), jnp.float32),
            pltpu.VMEM((RT, 1), jnp.float32),
            pltpu.VMEM((RT, 1), jnp.float32),
        ],
    )
    L = nbs * bs
    esize = jnp.dtype(k_pool.dtype).itemsize
    scale_bytes = 2.0 * B * KVH * L * 4 if kv_dtype is not None else 0.0
    return pl.pallas_call(
        functools.partial(_chunk_kernel, bs=bs, chunk=chunk,
                          n_pages=nbs, kv_dtype=kv_dtype),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, RT, D), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
        cost_estimate=pl.CostEstimate(
            flops=4.0 * B * KVH * RT * D * L,
            bytes_accessed=float(2 * B * L * KVH * D * esize)
            + scale_bytes,
            transcendentals=float(B * KVH * RT * L)),
        interpret=interpret,
        name=KERNEL_NAME,
    )(block_table, positions, *operands)


def _xla_chunked(q_g, k_pool, v_pool, block_table, positions, chunk,
                 k_scale=None, v_scale=None, kv_dtype=None):
    """Same grouped-query chunk attention in plain XLA: q_g is the
    ROTATED and pre-scaled [B, KVH, RT, D] f32 query (scale folded in,
    exactly as the caller hands the kernel)."""
    B, KVH, RT, D = q_g.shape
    bs = k_pool.shape[1]
    nbs = block_table.shape[1]
    L = nbs * bs
    if kv_dtype is not None:
        # same decode_codes * per-row-scale multiply as the kernel's
        # DMA boundary, just on the gathered [B,nbs,bs,KVH,D] copy
        kb = decode_codes(k_pool[block_table], kv_dtype) * \
            k_scale[block_table][..., None, None]
        vb = decode_codes(v_pool[block_table], kv_dtype) * \
            v_scale[block_table][..., None, None]
    else:
        kb = k_pool[block_table].astype(jnp.float32)    # [B,nbs,bs,KVH,D]
        vb = v_pool[block_table].astype(jnp.float32)
    kb = kb.reshape(B, L, KVH, D)
    vb = vb.reshape(B, L, KVH, D)
    scores = jnp.einsum("bkrd,blkd->bkrl", q_g, kb,
                        preferred_element_type=jnp.float32)
    k_pos = jnp.arange(L)
    q_pos = positions[:, None] + jnp.arange(RT) % chunk  # [B, RT]
    valid = k_pos[None, None, None, :] <= q_pos[:, None, :, None]
    scores = jnp.where(valid, scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    pexp = jnp.exp(scores - m)
    l = jnp.sum(pexp, axis=-1, keepdims=True)
    acc = jnp.einsum("bkrl,blkd->bkrd", pexp, vb,
                     preferred_element_type=jnp.float32)
    return acc / jnp.maximum(l, 1e-30)


def fused_chunked_attention(q, k_pool, v_pool, block_table, positions,
                            *, use_pallas=None, interpret=None,
                            k_scale=None, v_scale=None,
                            kv_cache_dtype=None):
    """Paged attention for one prefill chunk, fused end to end.

    q: [B, T, H, D] ROTATED queries for the chunk; k_pool/v_pool:
    [nb, bs, KVH, D] block pools ALREADY holding the chunk's scattered
    k/v; block_table: [B, max_blocks] int32; positions: [B] int32
    per-sequence chunk-start frontiers (query t of sequence b sits at
    ``positions[b] + t``).  Returns the attention context [B, T, H, D]
    in q's dtype — the drop-in replacement for models/llama.py's
    ``_paged_attn`` gather path (identical causal masking, so padded
    chunk tails produce the same discarded garbage rows).

    Quantized pools (``kv_cache_dtype`` of ``"int8"``/``"fp8"``) hand
    in int8 code pools plus per-row ``k_scale``/``v_scale`` [nb, bs]
    f32 sidecars; dequant happens at the kernel's block-DMA boundary
    (and identically in the XLA fallback).  The caller has already
    scatter-quantized the chunk's k/v into the pools.

    On TPU the gather + mask + softmax + context is one Pallas kernel
    with an online softmax; elsewhere the numerically-identical XLA
    lowering runs instead.
    """
    from ..core.flags import flag
    from .fusion import pallas_interpret_forced

    B, T, H, D = q.shape
    KVH = k_pool.shape[2]
    rep = H // KVH
    positions = jnp.asarray(positions, jnp.int32)
    scale = 1.0 / math.sqrt(D)

    if use_pallas is None:
        if pallas_interpret_forced() and _HAS_PLTPU:
            use_pallas, interpret = True, True
        else:
            use_pallas = bool(flag("use_pallas_kernels")) and \
                jax.default_backend() == "tpu" and _HAS_PLTPU
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # GQA grouping: head h = kvh * rep + r, so the grouped row index is
    # r * T + t and every row of group kvh reads KV head kvh
    q_g = q.reshape(B, T, KVH, rep, D).transpose(0, 2, 3, 1, 4) \
        .reshape(B, KVH, rep * T, D).astype(jnp.float32) * scale
    if use_pallas:
        out = _pallas_chunked(q_g, k_pool, v_pool, block_table,
                              positions, T, interpret,
                              k_scale=k_scale, v_scale=v_scale,
                              kv_dtype=kv_cache_dtype)
    else:
        out = _xla_chunked(q_g, k_pool, v_pool, block_table, positions,
                           T, k_scale=k_scale, v_scale=v_scale,
                           kv_dtype=kv_cache_dtype)
    return out.reshape(B, KVH, rep, T, D).transpose(0, 3, 1, 2, 4) \
        .reshape(B, T, H, D).astype(q.dtype)


# ---------------------------------------------------------------------------
# cost annotation (xray/shardplan price the pallas_call through this)
# ---------------------------------------------------------------------------

def _chunked_prefill_cost(in_avals, out_avals):
    # operand order fixed by _pallas_chunked:
    # (block_table, positions, q_g, k_pool, v_pool[, k_scale, v_scale])
    bt_shape = in_avals[0][0]
    q_shape, q_dtype = in_avals[2][0], in_avals[2][1]
    pool_shape, pool_dtype = in_avals[3][0], in_avals[3][1]
    B, nbs = int(bt_shape[0]), int(bt_shape[1])
    KVH, RT, D = int(q_shape[1]), int(q_shape[2]), int(q_shape[3])
    bs = int(pool_shape[1])
    L = nbs * bs
    flops = 4.0 * B * KVH * RT * D * L                  # qk^T + pv MACs
    trans = float(B * KVH * RT * L)                     # exp per score
    esize = np.dtype(pool_dtype).itemsize
    in_bytes = sum(
        float(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        for shape, dt in in_avals[:3])                  # table/pos/q
    # the pools are read THROUGH the block table: B*L rows each, not
    # the whole pool allocation (esize already reflects int8 when the
    # pool is quantized); per-row f32 scale sidecars ride along per
    # kv-head grid step when present
    kv_bytes = 2.0 * B * L * KVH * D * esize
    if len(in_avals) > 5:
        kv_bytes += 2.0 * B * KVH * L * \
            np.dtype(in_avals[5][1]).itemsize
    out_bytes = sum(
        float(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        for shape, dt in out_avals)
    return KernelCost(flops=flops, bytes_accessed=in_bytes + kv_bytes
                      + out_bytes, transcendentals=trans,
                      dtype=str(q_dtype))


register_kernel_cost(
    KERNEL_NAME, _chunked_prefill_cost,
    sample_in=[((2, 4), "int32"), ((2,), "int32"),
               ((2, 2, 8, 16), "float32"), ((8, 4, 2, 16), "float32"),
               ((8, 4, 2, 16), "float32")],
    sample_out=[((2, 2, 8, 16), "float32")])
