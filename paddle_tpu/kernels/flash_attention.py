"""Pallas flash attention for TPU.

TPU-native replacement for the reference fused attention CUDA stack
(/root/reference/paddle/fluid/operators/fused/fused_attention_op.cu,
fmha_ref.h): online-softmax tiling over the KV sequence so logits never
materialize in HBM.  Grid = (batch*heads, q_blocks, k_blocks) with the KV
axis innermost; m/l/acc accumulate in VMEM scratch across k steps and the
output block is written on the last k step.

Forward = Pallas kernel; backward recomputes through the XLA reference
(flash-style recompute: no O(T^2) residuals are saved).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only module; import lazily-safe for CPU test runs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _attn_reference(q, k, v, causal, scale):
    """[B, H, T, D] reference; also used for the recompute backward."""
    logits = jnp.einsum(
        "bhtd,bhsd->bhts", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        t, s = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((t, s), bool), k=s - t)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bhsd->bhtd", probs, v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                scale, causal, block_q, block_k, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)  # [block_q, d]
    k = k_ref[0].astype(jnp.float32)  # [block_k, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [block_q, block_k]

    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

    m_prev = m_ref[:]  # [block_q, 1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
    v = v_ref[0].astype(jnp.float32)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_ref[:] = m_new
    l_ref[:] = l_new

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(
            o_ref.dtype)


def _flash_fwd_bhtd(q, k, v, causal, scale, block_q, block_k, interpret):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    if Tq % bq or Tk % bk:
        # shape not tileable: fall back
        return _attn_reference(q, k, v, causal, scale)
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)

    grid = (B * H, Tq // bq, Tk // bk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        kv_len=Tk)
    scratch = [
        pltpu.VMEM((bq, D), jnp.float32) if _HAS_PLTPU and not interpret
        else pltpu.VMEM((bq, D), jnp.float32),
        pltpu.VMEM((bq, 1), jnp.float32),
        pltpu.VMEM((bq, 1), jnp.float32),
    ]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
    )(qr, kr, vr)
    return out.reshape(B, H, Tq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_bhtd(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd_bhtd(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_fwd_bhtd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    # flash-style: recompute attention under XLA and transpose (no O(T^2)
    # residual was stored by the forward kernel)
    _, vjp_fn = jax.vjp(
        lambda q_, k_, v_: _attn_reference(q_, k_, v_, causal, scale), q, k, v)
    return vjp_fn(g)


_flash_attention_bhtd.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_bhtd(q, k, v, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=None):
    """[B, H, T, D] flash attention."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not _HAS_PLTPU:
        return _attn_reference(q, k, v, causal, scale)
    return _flash_attention_bhtd(q, k, v, causal, scale, block_q, block_k,
                                 interpret)


def flash_attention_bthd(q, k, v, causal=False, scale=None, **kwargs):
    """[B, T, H, D] layout (paddle flash_attention layout).  Supports GQA by
    repeating KV heads when q heads are a multiple of kv heads."""
    qh = q.shape[2]
    kh = k.shape[2]
    if qh != kh:
        rep = qh // kh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = flash_attention_bhtd(qt, kt, vt, causal=causal, scale=scale, **kwargs)
    return jnp.swapaxes(out, 1, 2)
