"""Pallas flash attention for TPU.

TPU-native replacement for the reference fused attention CUDA stack
(/root/reference/paddle/fluid/operators/fused/fused_attention_op.cu,
fmha_ref.h): online-softmax tiling over the KV sequence so logits never
materialize in HBM.  Grid = (batch*heads, q_blocks, k_blocks) with the KV
axis innermost; m/l/acc accumulate in VMEM scratch across k steps and the
output block is written on the last k step.

Backward (round 2) = Pallas kernels too (FlashAttention-2 style): the
forward saves only O and the per-row logsumexp L; backward recomputes
P = exp(S - L) blockwise and runs two kernels — dQ (grid over q blocks,
kv innermost) and dK/dV (grid over kv blocks, q innermost) — so no O(T^2)
tensor ever lives in HBM in either direction.  XLA-recompute backward
remains the fallback for untileable shapes.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only module; import lazily-safe for CPU test runs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30
# TPU vector lanes: per-row scalars (lse, delta) are stored broadcast over a
# trailing lane dim so their blocks satisfy the (8, 128) tiling rule.
NUM_LANES = 128


def _attn_reference(q, k, v, causal, scale):
    """[B, H, T, D] reference; also used for the recompute backward."""
    logits = jnp.einsum(
        "bhtd,bhsd->bhts", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        t, s = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((t, s), bool), k=s - t)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bhsd->bhtd", probs, v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                scale, causal, block_q, block_k, offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)  # [block_q, d]
    k = k_ref[0].astype(jnp.float32)  # [block_k, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [block_q, block_k]

    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos + offset >= k_pos, s, NEG_INF)

    m_prev = m_ref[:]  # [block_q, 1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
    v = v_ref[0].astype(jnp.float32)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_ref[:] = m_new
    l_ref[:] = l_new

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(
            o_ref.dtype)


def _fwd_kernel_lse(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                    l_ref, *, scale, causal, block_q, block_k, offset):
    """Forward that also writes L = m + log(l) for the Pallas backward."""
    _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                scale=scale, causal=causal, block_q=block_q,
                block_k=block_k, offset=offset)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == nk - 1)
    def _write_lse():
        lse = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))  # [bq, 1]
        lse_ref[0] = jax.lax.broadcast_in_dim(
            lse[:, 0], lse_ref.shape[1:], (0,))


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale, causal, block_q, block_k, offset):
    """dQ = sum_k dS @ K * scale, dS = P * (dO V^T - D)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1]         # [bq, 1] (lanes are identical)
    delta = delta_ref[0][:, :1]     # [bq, 1]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos + offset >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lse)  # masked entries: exp(NEG_INF - lse) = 0
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    acc_ref[:] += jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, offset):
    """dV = P^T dO ; dK = dS^T Q * scale — grid over kv blocks, q inner."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1]
    delta = delta_ref[0][:, :1]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos + offset >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lse)  # [bq, bk]
    dv_acc[:] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale  # [bq, bk]
    dk_acc[:] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_fwd_bhtd(q, k, v, causal, scale, block_q, block_k, interpret):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    if Tq % bq or Tk % bk:
        # shape not tileable: fall back
        return _attn_reference(q, k, v, causal, scale)
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)

    grid = (B * H, Tq // bq, Tk // bk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        offset=Tk - Tq)
    scratch = [
        pltpu.VMEM((bq, D), jnp.float32) if _HAS_PLTPU and not interpret
        else pltpu.VMEM((bq, D), jnp.float32),
        pltpu.VMEM((bq, 1), jnp.float32),
        pltpu.VMEM((bq, 1), jnp.float32),
    ]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
    )(qr, kr, vr)
    return out.reshape(B, H, Tq, D)


def _tileable(Tq, Tk, block_q, block_k):
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    return (Tq % bq == 0 and Tk % bk == 0), bq, bk


def _flash_fwd_lse_bhtd(q, k, v, causal, scale, block_q, block_k, interpret):
    """Forward returning (out, lse) via the Pallas kernel."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    ok, bq, bk = _tileable(Tq, Tk, block_q, block_k)
    assert ok
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    grid = (B * H, Tq // bq, Tk // bk)
    kernel = functools.partial(
        _fwd_kernel_lse, scale=scale, causal=causal, block_q=bq, block_k=bk,
        offset=Tk - Tq)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, NUM_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tq, NUM_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
    )(qr, kr, vr)
    return out.reshape(B, H, Tq, D), lse[:, :, 0]


def _flash_bwd_bhtd(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                    interpret):
    """FlashAttention-2 backward: dq kernel + dkv kernel."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    ok, bq, bk = _tileable(Tq, Tk, block_q, block_k)
    assert ok
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    gr = g.reshape(B * H, Tq, D)
    # delta = rowsum(dO * O) — the 'D' vector of FlashAttention-2
    delta = jnp.sum(gr.astype(jnp.float32)
                    * out.reshape(B * H, Tq, D).astype(jnp.float32), axis=-1)
    # broadcast per-row scalars over lanes so blocks obey the (8,128) tiling
    lse_l = jnp.broadcast_to(lse[..., None], (*lse.shape, NUM_LANES))
    delta_l = jnp.broadcast_to(delta[..., None], (*delta.shape, NUM_LANES))

    common = dict(scale=scale, causal=causal, block_q=bq, block_k=bk,
                  offset=Tk - Tq)
    q_spec = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    kv_spec_dq = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, bq, NUM_LANES), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(B * H, Tq // bq, Tk // bk),
        in_specs=[q_spec, kv_spec_dq, kv_spec_dq, q_spec, row_spec,
                  row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
    )(qr, kr, vr, gr, lse_l, delta_l)

    # dkv: grid over kv blocks, q innermost
    q_spec2 = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0))
    kv_spec2 = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))
    row_spec2 = pl.BlockSpec((1, bq, NUM_LANES), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(B * H, Tk // bk, Tq // bq),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2,
                  row_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
                   jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
    )(qr, kr, vr, gr, lse_l, delta_l)
    return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
            dv.reshape(B, H, Tk, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_bhtd(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd_bhtd(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    ok, _, _ = _tileable(q.shape[2], k.shape[2], block_q, block_k)
    if not ok:
        out = _attn_reference(q, k, v, causal, scale)
        return out, (q, k, v, None, None)
    out, lse = _flash_fwd_lse_bhtd(q, k, v, causal, scale, block_q, block_k,
                                   interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if lse is None:
        # untileable shape: XLA recompute fallback
        _, vjp_fn = jax.vjp(
            lambda q_, k_, v_: _attn_reference(q_, k_, v_, causal, scale),
            q, k, v)
        return vjp_fn(g)
    return _flash_bwd_bhtd(q, k, v, out, lse, g, causal, scale, block_q,
                           block_k, interpret)


_flash_attention_bhtd.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


_AUTOTUNE_BLOCKS = [(128, 128), (128, 256), (256, 256), (256, 512),
                    (512, 512), (512, 1024)]


def _autotuned_blocks(q, k, causal, scale, interpret):
    """(block_q, block_k) via the autotune cache (FLAGS_use_autotune)."""
    from ..core.flags import flag
    from . import autotune as at

    if interpret or not flag("use_autotune"):
        return DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    key = (B, H, Tq, Tk, D, str(q.dtype), causal)
    if isinstance(q, jax.core.Tracer):
        # under a trace: timing is impossible; use a cached winner if one
        # exists for these (static) shapes, else the defaults
        return at.lookup("flash_attention", key) or (DEFAULT_BLOCK_Q,
                                                     DEFAULT_BLOCK_K)
    cands = [(bq, bk) for bq, bk in _AUTOTUNE_BLOCKS
             if Tq % min(bq, Tq) == 0 and Tk % min(bk, Tk) == 0]
    if not cands:
        return DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K

    v_probe = k  # same shape/dtype as v
    jitted = {}  # one compiled fn per cfg: the timed iters must hit the
    # jit cache, else the search measures XLA compile time, not kernels

    def run(cfg):
        fn = jitted.get(cfg)
        if fn is None:
            fn = jax.jit(functools.partial(
                _flash_fwd_bhtd, causal=causal, scale=scale,
                block_q=cfg[0], block_k=cfg[1], interpret=False))
            jitted[cfg] = fn
        fn(q, k, v_probe).block_until_ready()

    best = at.autotune("flash_attention", key, cands, run)
    return best or (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)


def flash_attention_bhtd(q, k, v, causal=False, scale=None,
                         block_q=None, block_k=None, interpret=None):
    """[B, H, T, D] flash attention."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not _HAS_PLTPU:
        return _attn_reference(q, k, v, causal, scale)
    if block_q is None or block_k is None:
        # explicit flag override (perf experiments: FLAGS_flash_block_q=…
        # env or set_flags) beats autotune/defaults
        from ..core.flags import flag

        block_q = block_q or (int(flag("flash_block_q")) or None)
        block_k = block_k or (int(flag("flash_block_k")) or None)
    if block_q is None or block_k is None:
        abq, abk = _autotuned_blocks(q, k, causal, scale, interpret)
        block_q = block_q or abq
        block_k = block_k or abk
    return _flash_attention_bhtd(q, k, v, causal, scale, block_q, block_k,
                                 interpret)


def flash_attention_bthd(q, k, v, causal=False, scale=None, **kwargs):
    """[B, T, H, D] layout (paddle flash_attention layout).  Supports GQA by
    repeating KV heads when q heads are a multiple of kv heads."""
    qh = q.shape[2]
    kh = k.shape[2]
    if qh != kh:
        rep = qh // kh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = flash_attention_bhtd(qt, kt, vt, causal=causal, scale=scale, **kwargs)
    return jnp.swapaxes(out, 1, 2)
