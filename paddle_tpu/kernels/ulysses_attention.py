"""Ulysses (DeepSpeed-Ulysses style) sequence parallelism via all-to-all.

The reference has NO sequence/context parallelism (SURVEY.md §2.2 last row);
this is new capability the TPU build owns, complementing ring attention
(`ring_attention.py`).  Where ring attention rotates KV shards around the
`sp` ring with ppermute, Ulysses re-shards with two all-to-alls: inputs
arrive sharded over the sequence axis [B, T/sp, H, D], an all-to-all inside
`shard_map` turns them into head-sharded full-sequence blocks [B, T, H/sp,
D], plain (flash) attention runs locally per head group, and a second
all-to-all restores sequence sharding.  Both all-to-alls ride ICI; the score
matrix only ever exists blockwise inside the local attention.

Trade-off vs ring: Ulysses moves 2x activations once (latency ~2 hops,
bandwidth-optimal for moderate sp), ring moves KV sp-1 times but overlaps
with compute; Ulysses needs heads % sp == 0, ring has no head constraint.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _shard_map(fn, mesh, in_specs, out_specs):
    # Lazy import: distributed/__init__ imports this module at load time.
    from ..distributed.mesh import shard_map_compat

    return shard_map_compat(fn, mesh, in_specs, out_specs)


def _plain_attention(q, k, v, causal, scale):
    """q/k/v: [B, T, H, D] (full sequence, local heads).  GQA-aware.

    On TPU this is the blockwise Pallas flash kernel (no T x T score matrix
    ever materializes); elsewhere the dense reference path.
    """
    if jax.default_backend() == "tpu":
        from .flash_attention import flash_attention_bthd

        return flash_attention_bthd(q, k, v, causal=causal, scale=scale)
    rep = q.shape[2] // k.shape[2]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0) >= \
            jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _ulysses_local(q, k, v, axis_name, causal, scale):
    """Runs on each sp shard inside shard_map.  q/k/v: [B, T_local, H, D]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # seq-sharded -> head-sharded: split heads (axis 2) across sp, gather the
    # full sequence (axis 1).  tiled=True keeps the block layout contiguous.
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            split_axis=2, concat_axis=1, tiled=True)
    qh, kh, vh = a2a(q), a2a(k), a2a(v)
    out = _plain_attention(qh, kh, vh, causal, scale)
    # head-sharded -> seq-sharded: inverse all-to-all
    return jax.lax.all_to_all(out, axis_name=axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh = None, axis_name: str = "sp",
                      causal: bool = False, scale=None,
                      batch_axis: str = None, head_axis: str = None):
    """[B, T, H, D] exact attention with T sharded over `axis_name`.

    Called on global (possibly sharded) arrays; returns the same layout.
    `head_axis` optionally names a mesh axis the head dim is already sharded
    over (tensor parallelism); the all-to-all then runs within each TP group.
    Requires local head count divisible by the sp degree.
    """
    from ..distributed.mesh import get_mesh

    mesh = mesh or get_mesh()
    if mesh is None or axis_name not in mesh.shape \
            or mesh.shape[axis_name] == 1:
        if scale is None:
            scale = 1.0 / math.sqrt(q.shape[-1])
        return _plain_attention(q, k, v, causal, scale)

    sp = mesh.shape[axis_name]
    n_kv_local = k.shape[2] // (mesh.shape.get(head_axis, 1)
                                if head_axis else 1)
    if n_kv_local % sp != 0:
        # head constraint not met (e.g. GQA with few KV heads): ring handles
        # this case without reshuffling heads
        from .ring_attention import ring_attention

        return ring_attention(q, k, v, mesh=mesh, axis_name=axis_name,
                              causal=causal, scale=scale,
                              batch_axis=batch_axis)

    spec = P(batch_axis, axis_name, head_axis, None)
    fn = _shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name, causal=causal,
                          scale=scale),
        mesh, (spec, spec, spec), spec)
    return fn(q, k, v)
