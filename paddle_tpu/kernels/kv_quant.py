# lint-tpu: disable-file=L004 -- kernel-layer quantization helpers
# (like paged_attention.py); direct jax use is the point here
"""Quantized paged-KV storage codecs shared by the serving cache, the
fused attention kernels, and their XLA fallbacks (ISSUE 20).

The paged block pools store KV as int8 CODES plus one float32 absmax
scale per (block, token) ROW — the scale reduces over the row's
(kv_heads x head_dim) elements.  Per-row scales are append-only: every
KV write quantizes exactly the rows it lands on, so quantization
happens inside the traced prefill/decode steps with no host sync
(H106) and no rescaling of previously-written codes (a per-block
SCALAR scale could not absorb a new token's larger absmax without
rewriting the whole block).

Two schemes, both in an int8 container so ONE pool layout serves both:

* ``"int8"`` — symmetric absmax: ``scale = absmax / 127``,
  ``code = round(clip(x / scale, -127, 127))``.
* ``"fp8"``  — fp8-e4m3 emulation: ``scale = absmax / 448`` (e4m3's
  max normal), codes are the e4m3 bit pattern bitcast into int8.  On
  CPU this is exact fp8 arithmetic via jax's ml_dtypes float8_e4m3fn;
  on TPU the same bitcast round-trips through the native fp8 type.

Dequant is ``decode_codes(codes) * scale`` in float32 — a multiply
fused into the block-DMA boundary of both Pallas kernels
(kernels/paged_attention.py, kernels/chunked_prefill.py) and written
IDENTICALLY in their XLA fallbacks, so CPU tier-1 tests the exact
served math.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: canonical scheme names (``None`` = unquantized full-precision pool)
KV_SCHEMES = ("int8", "fp8")

_ALIASES = {
    None: None, "": None, "fp32": None, "float32": None, "auto": None,
    "int8": "int8", "i8": "int8",
    "fp8": "fp8", "fp8_e4m3": "fp8", "float8_e4m3fn": "fp8",
}

#: clip/quantization range per scheme (e4m3 max normal is 448)
KV_QMAX = {"int8": 127.0, "fp8": 448.0}

#: numeric gauge codes (observability: serving_kv_cache_dtype)
KV_DTYPE_CODES = {None: 0, "int8": 1, "fp8": 2}


def resolve_kv_cache_dtype(name):
    """Canonicalize a ``ServingConfig.kv_cache_dtype`` spelling to
    ``None`` / ``"int8"`` / ``"fp8"`` (ValueError on anything else)."""
    if isinstance(name, str):
        name = name.lower()
    if name in _ALIASES:
        return _ALIASES[name]
    raise ValueError(
        f"unsupported kv_cache_dtype {name!r}; expected one of "
        f"{sorted(k for k in _ALIASES if isinstance(k, str))}")


def kv_storage_dtype(scheme):
    """Pool element dtype for ``scheme`` — int8 is the container for
    both schemes (fp8 codes are e4m3 bit patterns bitcast into int8)."""
    return jnp.int8 if scheme is not None else None


def kv_scale_bytes_per_block(block_size, scheme):
    """Scale-sidecar bytes ONE (k or v) block carries: one f32 absmax
    per token row, zero when unquantized."""
    return int(block_size) * 4 if scheme is not None else 0


def quantize_kv(x, scheme):
    """Quantize KV rows: ``x`` [..., KVH, D] float → (codes int8 of the
    same shape, scales f32 [...]) with one absmax scale per leading
    row.  All-zero rows get scale 1.0 so dequant stays exact."""
    qmax = KV_QMAX[scheme]
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=(-2, -1))
    scale = jnp.where(absmax > 0.0, absmax / qmax, 1.0)
    y = xf / scale[..., None, None]
    if scheme == "int8":
        codes = jnp.clip(jnp.round(y), -qmax, qmax).astype(jnp.int8)
    else:
        codes = jax.lax.bitcast_convert_type(
            jnp.clip(y, -qmax, qmax).astype(jnp.float8_e4m3fn), jnp.int8)
    return codes, scale.astype(jnp.float32)


def decode_codes(codes, scheme):
    """Codes → float32, WITHOUT the scale multiply (kernels apply the
    scale themselves with their own broadcast shape)."""
    if scheme == "int8":
        return codes.astype(jnp.float32)
    return jax.lax.bitcast_convert_type(
        codes, jnp.float8_e4m3fn).astype(jnp.float32)


def dequantize_kv(codes, scale, scheme):
    """Full dequant: ``codes`` [..., KVH, D] int8, ``scale`` f32 [...]
    per-row → float32 values."""
    return decode_codes(codes, scheme) * scale[..., None, None]


def kv_pool_dtype_code(scheme) -> int:
    return KV_DTYPE_CODES[scheme]


def kv_bytes_per_element(scheme, fallback_dtype=jnp.float32) -> int:
    """Element width of the stored KV codes (1 for both quantized
    schemes; the pool dtype's width otherwise)."""
    if scheme is not None:
        return 1
    return int(np.dtype(jnp.dtype(fallback_dtype)).itemsize)


__all__ = ["KV_SCHEMES", "KV_QMAX", "KV_DTYPE_CODES",
           "resolve_kv_cache_dtype", "kv_storage_dtype",
           "kv_scale_bytes_per_block", "quantize_kv", "decode_codes",
           "dequantize_kv", "kv_pool_dtype_code",
           "kv_bytes_per_element"]
