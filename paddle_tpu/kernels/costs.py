"""Per-kernel FLOP/byte cost annotations for the analysis layer.

A ``pallas_call`` is opaque to the jaxpr walkers: its inner jaxpr is
written in BLOCK shapes, so recursing into it multiplies every cost by
the grid and the analyzers either over-count wildly or fall back to an
elementwise guess.  Kernels instead register a cost function here,
keyed on the ``name=`` they pass to ``pl.pallas_call`` — ``xray``
prices the equation through the registry and ``shardplan`` treats the
call as a priced leaf instead of an unknown.

Entries are VALIDATED AT REGISTRATION: the cost function is evaluated
on a representative sample of abstract operands and the result is
checked (flops >= 0, bytes > 0, a transcendental count >= 0, dtype
names that resolve) so a bad annotation fails loudly at import time,
not as a silently-wrong roofline three layers up.

No jax import here — the registry must stay importable from analysis
code paths that refuse heavy imports.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

try:  # extended dtypes (bfloat16, float8_*, int4) live in ml_dtypes
    import ml_dtypes as _ml_dtypes
except Exception:  # pragma: no cover - ml_dtypes ships with jax
    _ml_dtypes = None

#: names numpy itself cannot resolve but kernels legitimately emit —
#: quantized pools (int8 containers holding fp8 bit patterns), bf16
#: accumulators, and sub-byte packed weights.  Resolved through
#: ml_dtypes, with the PACKED bytes-per-element recorded explicitly
#: (np.dtype(int4).itemsize says 1 because numpy pads to a byte).
_SUB_BYTE_ELEMENT_BYTES = {"int4": 0.5, "uint4": 0.5,
                           "float4_e2m1fn": 0.5}


def resolve_cost_dtype(name) -> np.dtype:
    """``np.dtype(name)`` that also understands ml_dtypes names
    (``bfloat16``, ``float8_e4m3fn``, ``int4``, ...) which plain numpy
    rejects.  Raises TypeError for genuinely unknown names."""
    try:
        return np.dtype(name)
    except TypeError:
        if _ml_dtypes is not None and isinstance(name, str):
            ext = getattr(_ml_dtypes, name, None)
            if ext is not None:
                return np.dtype(ext)
        raise


def dtype_element_bytes(name) -> float:
    """Bytes per element for cost accounting, as a float so sub-byte
    packed dtypes (int4 = 0.5) price correctly instead of rounding up
    to numpy's byte-padded itemsize."""
    if isinstance(name, str) and name in _SUB_BYTE_ELEMENT_BYTES:
        return _SUB_BYTE_ELEMENT_BYTES[name]
    dt = resolve_cost_dtype(name)
    if dt.name in _SUB_BYTE_ELEMENT_BYTES:
        return _SUB_BYTE_ELEMENT_BYTES[dt.name]
    return float(dt.itemsize)


@dataclasses.dataclass(frozen=True)
class KernelCost:
    """One kernel invocation's priced cost.

    flops / bytes_accessed cover the WHOLE call (all grid steps);
    transcendentals counts exp/log/rsqrt-class element ops, weighted by
    the analyzers the same way jaxpr transcendentals are.  ``dtype`` is
    the accumulation dtype name, recorded for the roofline breakdown.
    """

    flops: float
    bytes_accessed: float
    transcendentals: float = 0.0
    dtype: str = "float32"

    def __post_init__(self):
        if not (self.flops >= 0.0):
            raise ValueError(
                f"KernelCost.flops must be >= 0, got {self.flops!r}")
        if not (self.bytes_accessed > 0.0):
            raise ValueError(
                "KernelCost.bytes_accessed must be > 0 (every kernel "
                f"touches memory), got {self.bytes_accessed!r}")
        if not (self.transcendentals >= 0.0):
            raise ValueError(
                "KernelCost.transcendentals must be >= 0, got "
                f"{self.transcendentals!r}")
        try:
            resolve_cost_dtype(self.dtype)
        except TypeError as e:
            raise ValueError(
                f"KernelCost.dtype {self.dtype!r} is not a dtype "
                f"name numpy or ml_dtypes recognises") from e


#: abstract operand passed to cost functions: (shape tuple, dtype name)
AbstractArg = Tuple[Tuple[int, ...], str]

CostFn = Callable[[Sequence[AbstractArg], Sequence[AbstractArg]],
                  KernelCost]

_REGISTRY: Dict[str, CostFn] = {}


def register_kernel_cost(name: str, fn: CostFn, *,
                         sample_in: Sequence[AbstractArg],
                         sample_out: Sequence[AbstractArg]) -> CostFn:
    """Register ``fn`` as the cost model for pallas kernels named
    ``name`` (the ``pl.pallas_call(..., name=...)`` string).

    ``sample_in`` / ``sample_out`` are representative abstract operands
    the function is evaluated on RIGHT NOW — a cost function that
    raises, or returns something other than a valid KernelCost, fails
    here at import time instead of producing a silent garbage roofline.
    """
    if not name or not isinstance(name, str):
        raise ValueError(f"kernel cost name must be a non-empty string, "
                         f"got {name!r}")
    probe = fn(tuple(sample_in), tuple(sample_out))
    if not isinstance(probe, KernelCost):
        raise TypeError(
            f"cost fn for kernel {name!r} returned {type(probe).__name__}, "
            f"expected KernelCost")
    _REGISTRY[name] = fn
    return fn


def lookup_kernel_cost(name: str) -> Optional[CostFn]:
    return _REGISTRY.get(name)


def registered_kernels() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def price_eqn_avals(name: str,
                    in_avals: Sequence[AbstractArg],
                    out_avals: Sequence[AbstractArg]
                    ) -> Optional[KernelCost]:
    """Price one pallas_call occurrence; None when the kernel has no
    registered annotation (caller falls back to its generic guess)."""
    fn = _REGISTRY.get(name)
    if fn is None:
        return None
    return fn(tuple(in_avals), tuple(out_avals))


def _np_bytes(aval: AbstractArg) -> float:
    shape, dtype = aval
    n = 1
    for s in shape:
        n *= int(s)
    return float(n) * dtype_element_bytes(dtype)


def io_bytes(in_avals: Sequence[AbstractArg],
             out_avals: Sequence[AbstractArg]) -> float:
    """Sum of operand + result bytes — the natural bytes_accessed for a
    single-pass kernel (each operand read once, each output written
    once; that is the whole point of fusing)."""
    return (sum(_np_bytes(a) for a in in_avals)
            + sum(_np_bytes(a) for a in out_avals))
