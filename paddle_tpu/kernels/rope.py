"""Fused rotary position embedding (RoPE) Pallas kernel.

TPU-native replacement for the rotary step of the reference fused
attention ops (/root/reference/paddle/fluid/operators/fused/
fused_multi_transformer_op.cu applies rotary inline in its QKV kernel):
one VMEM pass applies the rotate-half formula to a [T_block, H*D] tile
with the cos/sin tables streamed per T block — no separate concat/mul/add
HLOs or doubled activation traffic.

Backward is RoPE with the angle negated (rotation matrices are
orthogonal), so the same kernel serves both directions.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BLOCK_T = 256


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref, *, H, D):
    bt = x_ref.shape[1]
    x = x_ref[0].astype(jnp.float32).reshape(bt, H, D)
    c = cos_ref[:].astype(jnp.float32)[:, None, :]  # [bt, 1, D/2]
    s = sin_ref[:].astype(jnp.float32)[:, None, :]
    x1 = x[..., : D // 2]
    x2 = x[..., D // 2:]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    o_ref[0] = out.reshape(bt, H * D).astype(o_ref.dtype)


def _rope_fwd(x, cos, sin, block_t, interpret):
    B, T, H, D = x.shape
    bt = min(block_t, T)
    if T % bt or (H * D) % 128 or D % 2:
        # untileable: plain XLA formula
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate(
            [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)
    xr = x.reshape(B, T, H * D)
    out = pl.pallas_call(
        functools.partial(_rope_kernel, H=H, D=D),
        grid=(B, T // bt),
        in_specs=[
            pl.BlockSpec((1, bt, H * D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((bt, D // 2), lambda b, i: (i, 0)),
            pl.BlockSpec((bt, D // 2), lambda b, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bt, H * D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, T, H * D), x.dtype),
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"))
        if (_HAS_PLTPU and not interpret) else None,
    )(xr, cos, sin)
    return out.reshape(B, T, H, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _rope(x, cos, sin, block_t, interpret):
    return _rope_fwd(x, cos, sin, block_t, interpret)


def _rope_vjp_fwd(x, cos, sin, block_t, interpret):
    return _rope_fwd(x, cos, sin, block_t, interpret), (cos, sin)


def _rope_vjp_bwd(block_t, interpret, res, g):
    cos, sin = res
    # inverse rotation: transpose of an orthogonal block-rotation
    return _rope_fwd(g, cos, -sin, block_t, interpret), None, None


_rope.defvjp(_rope_vjp_fwd, _rope_vjp_bwd)


def fused_rope(x, cos, sin, position_offset=0, block_t=DEFAULT_BLOCK_T,
               interpret=None):
    """Apply rotary embeddings to x: [B, T, H, D]; cos/sin: [maxT, D/2].

    Matches models/llama.py apply_rope (rotate-half convention)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    T = x.shape[1]
    c = jax.lax.dynamic_slice_in_dim(cos, position_offset, T)
    s = jax.lax.dynamic_slice_in_dim(sin, position_offset, T)
    return _rope(x, c, s, block_t, interpret)
