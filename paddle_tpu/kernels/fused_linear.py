"""Blocked matmul with fused bias/activation epilogue (Pallas).

TPU-native replacement for the reference fused GEMM-epilogue ops
(/root/reference/paddle/fluid/operators/fused/fused_gemm_epilogue_op.cu —
cublasLt matmul with BIAS/GELU epilogues): the epilogue runs in VMEM on
the final K step of a (M, N, K)-blocked matmul, so the pre-activation
matrix never round-trips through HBM.

Backward recomputes z = x @ w + b (one extra GEMM) and applies the
activation derivative, matching the reference's fused_gemm_epilogue_grad
with auxiliary-output disabled.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BM, DEFAULT_BN, DEFAULT_BK = 256, 256, 512

_ACTS = {
    "none": lambda z: z,
    "relu": jax.nn.relu,
    # exact (erf) gelu: paddle's F.gelu default and the reference
    # fused_gemm_epilogue's cublasLt GELU are both erf-based
    "gelu": functools.partial(jax.nn.gelu, approximate=False),
    "gelu_tanh": jax.nn.gelu,
    "silu": jax.nn.silu,
}


def _kernel(x_ref, w_ref, b_ref, o_ref, acc_ref, *, act, has_bias):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        x_ref[:].astype(jnp.float32), w_ref[:].astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _epilogue():
        z = acc_ref[:]
        if has_bias:
            z = z + b_ref[0].astype(jnp.float32)  # [bn] row broadcast
        o_ref[:] = _ACTS[act](z).astype(o_ref.dtype)


def _fused_linear_fwd(x, w, b, act, bm, bn, bk, interpret):
    M, K = x.shape
    N = w.shape[1]
    bm_, bn_, bk_ = min(bm, M), min(bn, N), min(bk, K)
    if M % bm_ or N % bn_ or K % bk_:
        z = x @ w
        if b is not None:
            z = z + b
        return _ACTS[act](z).astype(x.dtype)
    has_bias = b is not None
    # bias travels as [1, N] — 1-D operands hit XLA/Mosaic layout mismatches
    b_in = (b if has_bias else jnp.zeros((N,), x.dtype)).reshape(1, N)
    out = pl.pallas_call(
        functools.partial(_kernel, act=act, has_bias=has_bias),
        grid=(M // bm_, N // bn_, K // bk_),
        in_specs=[
            pl.BlockSpec((bm_, bk_), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk_, bn_), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn_), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm_, bn_), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm_, bn_), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
    )(x, w, b_in)
    return out


def _act_grad(act, z):
    if act == "none":
        return jnp.ones_like(z)
    return jax.grad(lambda t: jnp.sum(_ACTS[act](t)))(z)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fused_linear(x, w, b, act, bm, bn, bk, interpret):
    return _fused_linear_fwd(x, w, b, act, bm, bn, bk, interpret)


def _vjp_fwd(x, w, b, act, bm, bn, bk, interpret):
    return _fused_linear_fwd(x, w, b, act, bm, bn, bk, interpret), (x, w, b)


def _vjp_bwd(act, bm, bn, bk, interpret, res, g):
    x, w, b = res
    z = (x @ w).astype(jnp.float32)  # recompute pre-activation
    if b is not None:
        z = z + b.astype(jnp.float32)
    dz = (g.astype(jnp.float32) * _act_grad(act, z))
    dx = (dz @ w.astype(jnp.float32).T).astype(x.dtype)
    dw = (x.astype(jnp.float32).T @ dz).astype(w.dtype)
    db = dz.sum(axis=0).astype(b.dtype) if b is not None else None
    return dx, dw, db


_fused_linear.defvjp(_vjp_fwd, _vjp_bwd)


def fused_linear(x, w, bias=None, activation="none",
                 bm=DEFAULT_BM, bn=DEFAULT_BN, bk=DEFAULT_BK,
                 interpret=None):
    """activation(x @ w + bias) with the epilogue fused into the matmul.

    x: [..., K]; w: [K, N]; bias: [N] or None.
    activation: none | relu | gelu | silu."""
    if activation not in _ACTS:
        raise ValueError(f"unsupported activation {activation!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = x.shape[:-1]
    K = x.shape[-1]
    x2 = x.reshape(-1, K)
    out = _fused_linear(x2, w, bias, activation, bm, bn, bk, interpret)
    return out.reshape(*lead, w.shape[1])
