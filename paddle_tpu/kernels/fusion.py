"""Serving-fusion mode: one switch for the fused decode hot path.

The fused paged-attention decode kernel and the RMSNorm->matmul
epilogue fusions change WHICH program the model traces to, so the
decision must be made at trace time and must be consistent for the
lifetime of a compiled step (the zero-retrace contract).  The step
builders in models/generation.py resolve the mode ONCE per step and
pin it around the traced body with ``serving_fusion(...)``; the model
code consults ``fusion_enabled()`` wherever the fused and unfused
paths fork.

Resolution order:
  1. an active ``serving_fusion(...)`` context (the step builders);
  2. else the default: FLAGS_use_fused_serving AND a TPU backend.

On CPU the fused path lowers to the numerically-identical XLA
fallback, so forcing it on (``serving_fusion(True)`` /
``ServingConfig(fused_kernels=True)``) is how tier-1 and CI cover the
exact fused math without a TPU.
"""
from __future__ import annotations

import contextlib
import threading

import jax

_tls = threading.local()


def _default_enabled() -> bool:
    from ..core.flags import flag

    return bool(flag("use_fused_serving")) and \
        jax.default_backend() == "tpu"


def fusion_enabled() -> bool:
    """The trace-time fused/unfused fork the model code consults."""
    override = getattr(_tls, "override", None)
    if override is not None:
        return bool(override)
    return _default_enabled()


def resolve_serving_fusion(fused=None) -> bool:
    """Pin a step's fusion mode: an explicit request wins, else the
    flag/backend default.  Called once per step build so the compiled
    program never flips mode between calls."""
    if fused is None:
        return _default_enabled()
    return bool(fused)


@contextlib.contextmanager
def serving_fusion(enabled: bool):
    """Force the fusion mode for the duration (used around traced step
    bodies; runs at trace time, costs nothing per executed step)."""
    prev = getattr(_tls, "override", None)
    _tls.override = bool(enabled)
    try:
        yield
    finally:
        _tls.override = prev


def pallas_interpret_forced() -> bool:
    """True inside a ``force_pallas_interpret()`` context: the fused
    kernels resolve ``use_pallas=True, interpret=True`` regardless of
    backend, so the traced program carries the REAL pallas_call leaves.
    Off-TPU the fused steps normally lower to the XLA fallback, which is
    right for execution but blinds static analysis: the fusion miner's
    F004 already-fused accounting and the priced-pallas CI gates need
    the kernel to appear in the jaxpr on any backend."""
    return bool(getattr(_tls, "force_interpret", False))


@contextlib.contextmanager
def force_pallas_interpret(enabled: bool = True):
    """Trace-time context: fused kernels that would pick the XLA
    fallback off-TPU take the Pallas path in interpret mode instead
    (analysis-only — interpret execution is slow and never the serving
    path)."""
    prev = getattr(_tls, "force_interpret", None)
    _tls.force_interpret = bool(enabled)
    try:
        yield
    finally:
        _tls.force_interpret = prev
