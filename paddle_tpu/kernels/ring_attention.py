"""Ring attention: exact attention over sequence shards.

The reference has NO sequence/context parallelism (SURVEY.md §2.2 last row);
this is new capability the TPU build owns.  Design (Ring Attention /
blockwise): the sequence axis is sharded over the mesh axis `sp`; each step
of a fori_loop computes a blockwise online-softmax update against the
currently-held KV shard, then rotates KV one hop around the ring with
lax.ppermute over ICI — compute and the permute overlap, and the full T x T
score matrix never exists.

Composes with dp/mp as extra mesh axes via shard_map.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attn_update(q, k, v, acc, m, l, q_offset, kv_offset, scale, causal):
    """One online-softmax update of (acc, m, l) with a KV block.

    q: [B, Tq, H, D]; k/v: [B, Tk, Hkv, D]; acc: [B, Tq, H, D] f32;
    m/l: [B, Tq, H, 1] f32.  GQA (Hkv < H) is expanded here, after the ring
    hop, so only the small KV shard rides ICI.
    """
    rep = q.shape[2] // k.shape[2]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale  # [B,H,Tq,Tk]
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        k_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    s = jnp.moveaxis(s, 1, 2)[..., None, :]  # [B,Tq,H,1,Tk] align with m/l
    s = s[..., 0, :]  # [B,Tq,H,Tk]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    # guard fully-masked blocks (exp(NEG_INF - NEG_INF) = 1 otherwise)
    p = jnp.exp(s - m_new)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    alpha = jnp.exp(m - m_new)
    alpha = jnp.where(m <= NEG_INF / 2, 0.0, alpha)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bthk,bkhd->bthd", p, v.astype(jnp.float32))
    acc_new = acc * alpha + pv
    return acc_new, m_new, l_new


def _ring_attention_local(q, k, v, axis_name, causal, scale, ring_size=None):
    """Runs on each sp shard inside shard_map.  q/k/v: [B, T_local, H, D].

    ``ring_size`` is the static sp degree (the fori_loop trip count must
    be concrete; jax.lax.axis_size does not exist on older jax)."""
    n = ring_size if ring_size is not None else jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)

    acc0 = jnp.zeros((B, T, H, D), jnp.float32)
    m0 = jnp.full((B, T, H, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, T, H, 1), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (idx - i) % n  # whose KV shard we hold at step i
        q_off = idx * T
        kv_off = src * T
        acc, m, l = _block_attn_update(q, k_cur, v_cur, acc, m, l, q_off,
                                       kv_off, scale, causal)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    acc, m, l, _, _ = jax.lax.fori_loop(0, n, body, (acc0, m0, l0, k, v))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh = None, axis_name: str = "sp",
                   causal: bool = False, scale=None, batch_axis: str = None):
    """[B, T, H, D] exact attention with T sharded over `axis_name`.

    Called on global (possibly sharded) arrays; returns the same layout.
    """
    from ..distributed.mesh import get_mesh

    mesh = mesh or get_mesh()
    if mesh is None or axis_name not in mesh.shape:
        # no sp axis: plain attention (GQA-aware; flash kernel on TPU)
        from .ulysses_attention import _plain_attention

        return _plain_attention(q, k, v, causal,
                                scale or 1.0 / math.sqrt(q.shape[-1]))

    from ..distributed.mesh import shard_map_compat

    spec = P(batch_axis, axis_name, None, None)
    fn = shard_map_compat(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale,
                          ring_size=int(mesh.shape[axis_name])),
        mesh, (spec, spec, spec), spec)
    return fn(q, k, v)
