"""Pallas fused RMSNorm for TPU.

Replaces the reference's fused-norm CUDA kernels (the reference fuses
LayerNorm into fused_attention/fused_feedforward ops,
/root/reference/paddle/fluid/operators/fused/).  One pass over rows in VMEM:
mean-square, rsqrt, scale — saving an HBM round trip vs unfused.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BLOCK_ROWS = 512


def _rms_ref(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).astype(
        x.dtype) * w


def _kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    normed = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (normed * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_norm(x2d, w, eps, interpret):
    return _rms_fwd_impl(x2d, w, eps, interpret)


def _rms_fwd_impl(x2d, w, eps, interpret):
    n, d = x2d.shape
    rows = min(DEFAULT_BLOCK_ROWS, n)
    if n % rows:
        return _rms_ref(x2d, w, eps)
    return pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        grid=(n // rows,),
        in_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2d.dtype),
        interpret=interpret,
    )(x2d, w)


def _rms_vjp_fwd(x2d, w, eps, interpret):
    return _rms_fwd_impl(x2d, w, eps, interpret), (x2d, w)


def _rms_vjp_bwd(eps, interpret, res, g):
    x2d, w = res
    _, vjp_fn = jax.vjp(lambda x_, w_: _rms_ref(x_, w_, eps), x2d, w)
    return vjp_fn(g)


_rms_norm.defvjp(_rms_vjp_fwd, _rms_vjp_bwd)


def rms_norm(x, weight, epsilon=1e-6, interpret=None):
    """RMSNorm over the last axis; any leading shape."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not _HAS_PLTPU:
        return _rms_ref(x, weight, epsilon)
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    out = _rms_norm(x2d, weight, epsilon, interpret)
    return out.reshape(shape)
