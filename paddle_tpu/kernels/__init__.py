"""Pallas TPU kernel pack (SURVEY.md §7 step 8).

Replaces the reference's hand-written CUDA fused ops
(/root/reference/paddle/fluid/operators/fused/) with Mosaic-compiled Pallas
kernels.  Every kernel has an XLA reference path used on CPU (tests run the
Pallas interpreter) and as the recompute backward.
"""
from .flash_attention import flash_attention_bhtd, flash_attention_bthd  # noqa: F401
from .rms_norm import rms_norm  # noqa: F401
from .ulysses_attention import ulysses_attention  # noqa: F401
