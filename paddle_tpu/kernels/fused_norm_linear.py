"""RMSNorm->matmul prologue fusion (Pallas) with an XLA fallback.

The Llama block enters attention and the MLP through the same shape of
boundary: RMSNorm, then one or more matmuls over the SAME normalized
activation.  Unfused, the normalized [M, K] matrix round-trips HBM
between the norm and every projection.  Fused, only the [M] row-scale
vector ``rsqrt(mean(x^2) + eps)`` is materialized (``rms_scale`` — a
few KiB); each projection then applies the scale and the norm weight
to the x tile IN VMEM as the matmul's prologue, with the optional
activation as its epilogue (kernels/fused_linear.py's epilogue idiom,
extended upward into the producer).

Math contract (must mirror models/llama.py LlamaRMSNorm + Linear):

    normed = (x_f32 * rsqrt(mean(x_f32^2) + eps)).astype(x.dtype) * nw
    out    = act(normed @ w)

The XLA fallback composes exactly this expression, so CPU tier-1 and
the jaxpr audits cover the fused math without a pallas_call.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from .costs import KernelCost, register_kernel_cost
from .fused_linear import _ACTS, DEFAULT_BK, DEFAULT_BM, DEFAULT_BN

KERNEL_NAME = "fused_norm_linear"
_LANES = 128


def rms_scale(x, eps):
    """Per-row RMSNorm scale in f32: rsqrt(mean(x^2) + eps), shape
    [..., 1].  The ONLY intermediate the fused path materializes."""
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    return jax.lax.rsqrt(var + eps)


def _norm_linear_ref(x2d, rs, nw, w, act):
    normed = (x2d.astype(jnp.float32) * rs).astype(x2d.dtype) * nw
    z = jnp.dot(normed.astype(jnp.float32), w.astype(jnp.float32),
                preferred_element_type=jnp.float32)
    return _ACTS[act](z).astype(x2d.dtype)


def _kernel(x_ref, rs_ref, nw_ref, w_ref, o_ref, acc_ref, *, act,
            x_dtype):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # prologue: norm the x tile in VMEM — scale rows by rs, columns by
    # the norm weight, with the unfused path's exact cast points
    xb = x_ref[:].astype(jnp.float32) * rs_ref[:, 0:1]
    normed = xb.astype(x_dtype) * nw_ref[0]
    acc_ref[:] += jax.lax.dot_general(
        normed.astype(jnp.float32), w_ref[:].astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _epilogue():
        o_ref[:] = _ACTS[act](acc_ref[:]).astype(o_ref.dtype)


def _norm_linear_pallas(x2d, rs, nw, w, act, bm, bn, bk, interpret):
    M, K = x2d.shape
    N = w.shape[1]
    bm_, bn_, bk_ = min(bm, M), min(bn, N), min(bk, K)
    if M % bm_ or N % bn_ or K % bk_:
        return _norm_linear_ref(x2d, rs, nw, w, act)
    # row scale travels lane-broadcast (a 1-wide trailing dim is not a
    # legal TPU tile); norm weight as a [1, K] row (fused_linear's bias
    # idiom)
    rs_b = jnp.broadcast_to(rs.astype(jnp.float32), (M, _LANES))
    nw_row = nw.reshape(1, K)
    return pl.pallas_call(
        functools.partial(_kernel, act=act, x_dtype=x2d.dtype),
        grid=(M // bm_, N // bn_, K // bk_),
        in_specs=[
            pl.BlockSpec((bm_, bk_), lambda i, j, k: (i, k)),
            pl.BlockSpec((bm_, _LANES), lambda i, j, k: (i, 0)),
            pl.BlockSpec((1, bk_), lambda i, j, k: (0, k)),
            pl.BlockSpec((bk_, bn_), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((bm_, bn_), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x2d.dtype),
        scratch_shapes=[pltpu.VMEM((bm_, bn_), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
        cost_estimate=pl.CostEstimate(
            flops=2.0 * M * N * K,
            bytes_accessed=float((M * K + K * N + M * N)
                                 * jnp.dtype(x2d.dtype).itemsize),
            transcendentals=0.0),
        interpret=interpret,
        name=KERNEL_NAME,
    )(x2d, rs_b, nw_row, w)


def _autotuned_tiles(x2d, w, act, interpret):
    """(bm, bn, bk) via the autotune cache (FLAGS_use_autotune)."""
    from ..core.flags import flag
    from . import autotune as at

    defaults = (DEFAULT_BM, DEFAULT_BN, DEFAULT_BK)
    if interpret or not flag("use_autotune"):
        return defaults
    M, K = x2d.shape
    N = w.shape[1]
    key = (M, K, N, str(x2d.dtype), act)
    if isinstance(x2d, jax.core.Tracer):
        return at.lookup("fused_norm_linear", key) or defaults
    cands = [(bm, bn, bk)
             for bm in (128, 256, 512) for bn in (128, 256, 512)
             for bk in (256, 512)
             if M % min(bm, M) == 0 and N % min(bn, N) == 0
             and K % min(bk, K) == 0]
    if not cands:
        return defaults
    rs = rms_scale(x2d, 1e-5)
    nw = jnp.ones((K,), x2d.dtype)
    jitted = {}

    def run(cfg):
        fn = jitted.get(cfg)
        if fn is None:
            fn = jax.jit(functools.partial(
                _norm_linear_pallas, act=act, bm=cfg[0], bn=cfg[1],
                bk=cfg[2], interpret=False))
            jitted[cfg] = fn
        jax.block_until_ready(fn(x2d, rs, nw, w))

    best = at.autotune("fused_norm_linear", key, cands, run)
    return best or defaults


def fused_norm_linear(x, row_scale, norm_weight, w, activation="none",
                      bm=None, bn=None, bk=None, use_pallas=None,
                      interpret=None):
    """act(((x * row_scale).astype(x.dtype) * norm_weight) @ w) with the
    norm applied as the matmul's VMEM prologue.

    x: [..., K]; row_scale: [..., 1] f32 from ``rms_scale`` (computed
    ONCE and shared by every projection off the same normalized
    activation); norm_weight: [K]; w: [K, N].
    """
    from ..core.flags import flag
    from .fusion import pallas_interpret_forced

    if activation not in _ACTS:
        raise ValueError(f"unsupported activation {activation!r}")
    if use_pallas is None and pallas_interpret_forced() and _HAS_PLTPU:
        use_pallas, interpret = True, True
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if use_pallas is None:
        use_pallas = bool(flag("use_pallas_kernels")) and \
            jax.default_backend() == "tpu" and _HAS_PLTPU
    lead = x.shape[:-1]
    K = x.shape[-1]
    x2d = x.reshape(-1, K)
    rs = row_scale.reshape(-1, 1)
    if use_pallas:
        if bm is None or bn is None or bk is None:
            abm, abn, abk = _autotuned_tiles(x2d, w, activation, interpret)
            bm, bn, bk = bm or abm, bn or abn, bk or abk
        out = _norm_linear_pallas(x2d, rs, norm_weight, w, activation,
                                  bm, bn, bk, interpret)
    else:
        out = _norm_linear_ref(x2d, rs, norm_weight, w, activation)
    return out.reshape(*lead, w.shape[1])


def fused_rmsnorm_linear(x, norm_weight, w, eps, activation="none",
                         **kwargs):
    """Single-projection convenience: rms_scale + fused_norm_linear."""
    return fused_norm_linear(x, rms_scale(x, eps), norm_weight, w,
                             activation, **kwargs)


def _norm_linear_cost(in_avals, out_avals):
    # operand order fixed by _norm_linear_pallas: (x, rs, nw, w)
    (x_shape, x_dtype), _, _, (w_shape, w_dtype) = in_avals
    M, K = int(x_shape[0]), int(x_shape[1])
    N = int(w_shape[1])
    xe = np.dtype(x_dtype).itemsize
    we = np.dtype(w_dtype).itemsize
    out_bytes = sum(
        float(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        for shape, dt in out_avals)
    return KernelCost(
        flops=2.0 * M * N * K + 2.0 * M * K,            # matmul + norm
        bytes_accessed=float(M * K * xe + K * N * we + M * (_LANES * 4)
                             + K * xe) + out_bytes,
        transcendentals=0.0, dtype=str(x_dtype))


register_kernel_cost(
    KERNEL_NAME, _norm_linear_cost,
    sample_in=[((64, 64), "float32"), ((64, _LANES), "float32"),
               ((1, 64), "float32"), ((64, 128), "float32")],
    sample_out=[((64, 128), "float32")])
