"""Kernel tile-size autotuner with a persistent disk cache.

TPU-native analog of the reference kernel autotune machinery
(/root/reference/paddle/phi/kernels/autotune/cache.h AutoTuneCache and
switch_autotune.h): candidate tile configs are timed once on the real
device, and the winner is cached keyed on (op, shape signature, dtype) —
in memory for the process and as JSON on disk across processes.

Gated by FLAGS_use_autotune (core/flags); without it callers use their
static defaults and never pay the search.

Cache keys are CHIP-QUALIFIED: the same op/shape tunes differently on
v5e vs v6e vs the CPU fallback, so the accelerator kind is stamped
into every key.  ``--retune`` (bench.py) or PADDLE_TPU_RETUNE=1 is the
escape hatch: cached winners are ignored and re-measured once, then
the fresh result overwrites the disk cache.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

_mem_cache: Dict[str, Any] = {}
_disk_loaded = False
_dirty = False
_chip_name: Optional[str] = None
_retune = False


def _chip() -> str:
    """Accelerator kind for the cache key (e.g. ``TPU_v5e`` or
    ``cpu``) — resolved once; device enumeration is not free."""
    global _chip_name
    if _chip_name is None:
        try:
            import jax

            kind = jax.devices()[0].device_kind
            _chip_name = str(kind).strip().replace(" ", "_") or \
                jax.default_backend()
        except Exception:
            _chip_name = "unknown"
    return _chip_name


def set_retune(enabled: bool):
    """Ignore cached winners and re-measure (bench --retune)."""
    global _retune
    _retune = bool(enabled)


def retune_enabled() -> bool:
    return _retune or os.environ.get("PADDLE_TPU_RETUNE", "") in (
        "1", "true", "True")


def _cache_path() -> str:
    base = os.environ.get("PADDLE_TPU_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu")
    return os.path.join(base, "autotune.json")


def _load_disk():
    global _disk_loaded
    if _disk_loaded:
        return
    _disk_loaded = True
    try:
        with open(_cache_path()) as f:
            disk = json.load(f)
        for k, v in disk.items():
            _mem_cache.setdefault(k, v)
    except Exception:
        pass


def _save_disk():
    global _dirty
    if not _dirty:
        return
    try:
        path = _cache_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # atomic publish (resilience tmp+fsync+rename idiom): a reader
        # racing this write sees either the old cache or the new one,
        # never a torn JSON file
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(_mem_cache, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _dirty = False
    except Exception:
        pass


def cache_key(op: str, *parts) -> str:
    """(chip, op, shape-key) — the chip prefix keeps one shared disk
    cache correct across accelerator generations."""
    return f"{_chip()}|{op}|" + "|".join(str(p) for p in parts)


def autotune(op: str, key_parts: Iterable,
             candidates: Iterable[Tuple],
             run_fn: Callable[[Tuple], Any],
             warmup: int = 1, iters: int = 3) -> Optional[Tuple]:
    """Return the fastest candidate config for this key.

    run_fn(config) must execute the kernel end-to-end and block until the
    result is ready.  Configs that raise are skipped.  The winner persists
    to disk; subsequent processes skip the search entirely.
    """
    global _dirty
    _load_disk()
    key = cache_key(op, *key_parts)
    hit = _mem_cache.get(key)
    if hit is not None and not retune_enabled():
        return tuple(hit)

    best, best_t = None, float("inf")
    for cfg in candidates:
        try:
            for _ in range(warmup):
                run_fn(cfg)
            t0 = time.perf_counter()
            for _ in range(iters):
                run_fn(cfg)
            dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue
        if dt < best_t:
            best, best_t = cfg, dt
    if best is not None:
        _mem_cache[key] = list(best)
        _dirty = True
        _save_disk()
    return best


def lookup(op: str, key_parts: Iterable) -> Optional[Tuple]:
    """Cache-only probe (no search) — safe under a jit trace, where timing
    is impossible but shapes are static so prior results still apply."""
    _load_disk()
    hit = _mem_cache.get(cache_key(op, *key_parts))
    return tuple(hit) if hit is not None else None


def clear(disk: bool = False):
    _mem_cache.clear()
    if disk:
        try:
            os.remove(_cache_path())
        except OSError:
            pass
