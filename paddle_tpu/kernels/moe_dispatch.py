"""MoE token dispatch/combine Pallas kernels.

TPU-native replacement for the reference MoE routing collectives+kernels
(/root/reference/paddle/fluid/operators/collective/global_scatter_op.* and
incubate moe_layer's dispatch): GShard-style capacity-padded routing
expressed as one-hot matmuls, with the [T, E*C] one-hot built ON THE FLY
in VMEM from the (expert, slot) index pairs — the XLA einsum formulation
must materialize that one-hot in HBM (T*E*C floats, often larger than the
activations themselves).

dispatch:  tokens [T, M] → [E, C, M]   (weights optional)
combine :  expert_out [E, C, M], gates → [T, M]
Both are custom-vjp pairs of each other, so grads stay kernel-fused.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_BT = 256
DEFAULT_BC = 128


def moe_capacity(tokens: int, experts: int, top_k: int,
                 capacity_factor: float = 1.0) -> int:
    """GShard expert capacity: ceil(capacity_factor * T * K / E), the C
    in the padded [E, C, M] dispatch buffer."""
    return max(1, -(-int(tokens * top_k * capacity_factor) // experts))


def _resolve_interpret(interpret):
    """None → real kernel on TPU, XLA one-hot einsum fallback elsewhere
    (keeps CPU traces analyzable: the static analyzers and tier-1 see
    plain einsums instead of an opaque interpreted pallas_call).
    Explicit True still forces pallas interpret mode (kernel-logic
    parity testing); explicit False demands the real kernel."""
    if interpret is None:
        return False if jax.default_backend() == "tpu" else "xla"
    return interpret


def _dispatch_kernel(tok_ref, eidx_ref, sidx_ref, w_ref, o_ref, acc_ref, *,
                     expert_block_c0, K, bc):
    e = pl.program_id(0)
    ci = pl.program_id(1)
    ti = pl.program_id(2)
    nt = pl.num_programs(2)

    @pl.when(ti == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    tok = tok_ref[:].astype(jnp.float32)          # [bt, M]
    bt = tok.shape[0]
    c0 = ci * bc
    slots = jax.lax.broadcasted_iota(jnp.int32, (bt, bc), 1) + c0
    p = jnp.zeros((bt, bc), jnp.float32)
    for k in range(K):  # K is tiny (top-1/top-2)
        ek = eidx_ref[:, k][:, None]
        sk = sidx_ref[:, k][:, None]
        wk = w_ref[:, k][:, None].astype(jnp.float32)
        p = p + jnp.where((ek == e) & (sk == slots), wk, 0.0)
    acc_ref[:] += jax.lax.dot_general(
        p, tok, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ti == nt - 1)
    def _finalize():
        o_ref[0] = acc_ref[:].astype(o_ref.dtype)


def _combine_kernel(eo_ref, eidx_ref, sidx_ref, w_ref, o_ref, acc_ref, *,
                    C, K, bj):
    ti = pl.program_id(0)
    ji = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(ji == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    eo = eo_ref[:].astype(jnp.float32)  # [bj, M] slice of [E*C, M]
    bt = eidx_ref.shape[0]
    j0 = ji * bj
    cols = jax.lax.broadcasted_iota(jnp.int32, (bt, bj), 1) + j0
    p = jnp.zeros((bt, bj), jnp.float32)
    for k in range(K):
        flat = (eidx_ref[:, k] * C + sidx_ref[:, k])[:, None]
        wk = w_ref[:, k][:, None].astype(jnp.float32)
        p = p + jnp.where(flat == cols, wk, 0.0)
    acc_ref[:] += jax.lax.dot_general(
        p, eo, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ji == nj - 1)
    def _finalize():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _dispatch_raw(tokens, eidx, sidx, weights, E, C, bt, bc, interpret):
    if interpret == "xla":
        return _dispatch_xla(tokens, eidx, sidx, weights, E, C)
    T, M = tokens.shape
    K = eidx.shape[1]
    bt_ = min(bt, T)
    bc_ = min(bc, C)
    if T % bt_ or C % bc_:
        return _dispatch_xla(tokens, eidx, sidx, weights, E, C)
    out = pl.pallas_call(
        functools.partial(_dispatch_kernel, expert_block_c0=0, K=K, bc=bc_),
        grid=(E, C // bc_, T // bt_),
        in_specs=[
            pl.BlockSpec((bt_, M), lambda e, c, t: (t, 0)),
            pl.BlockSpec((bt_, K), lambda e, c, t: (t, 0)),
            pl.BlockSpec((bt_, K), lambda e, c, t: (t, 0)),
            pl.BlockSpec((bt_, K), lambda e, c, t: (t, 0)),
        ],
        out_specs=pl.BlockSpec((1, bc_, M), lambda e, c, t: (e, c, 0)),
        out_shape=jax.ShapeDtypeStruct((E, C, M), tokens.dtype),
        scratch_shapes=[pltpu.VMEM((bc_, M), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
    )(tokens, eidx, sidx, weights)
    return out


def _combine_raw(expert_out, eidx, sidx, weights, bt, bj, interpret):
    if interpret == "xla":
        return _combine_xla(expert_out, eidx, sidx, weights)
    E, C, M = expert_out.shape
    T, K = eidx.shape
    bt_ = min(bt, T)
    bj_ = min(bj, E * C)
    if T % bt_ or (E * C) % bj_:
        return _combine_xla(expert_out, eidx, sidx, weights)
    eo = expert_out.reshape(E * C, M)
    out = pl.pallas_call(
        functools.partial(_combine_kernel, C=C, K=K, bj=bj_),
        grid=(T // bt_, (E * C) // bj_),
        in_specs=[
            pl.BlockSpec((bj_, M), lambda t, j: (j, 0)),
            pl.BlockSpec((bt_, K), lambda t, j: (t, 0)),
            pl.BlockSpec((bt_, K), lambda t, j: (t, 0)),
            pl.BlockSpec((bt_, K), lambda t, j: (t, 0)),
        ],
        out_specs=pl.BlockSpec((bt_, M), lambda t, j: (t, 0)),
        out_shape=jax.ShapeDtypeStruct((T, M), expert_out.dtype),
        scratch_shapes=[pltpu.VMEM((bt_, M), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
    )(eo, eidx, sidx, weights)
    return out


def _dispatch_xla(tokens, eidx, sidx, weights, E, C):
    onehot = (jax.nn.one_hot(eidx, E, dtype=tokens.dtype)[..., None]
              * jax.nn.one_hot(sidx, C, dtype=tokens.dtype)[..., None, :])
    onehot = (onehot * weights[..., None, None].astype(tokens.dtype)).sum(1)
    return jnp.einsum("tec,tm->ecm", onehot, tokens)


def _combine_xla(expert_out, eidx, sidx, weights):
    gathered = expert_out[eidx, sidx]  # [T, K, M]
    return (gathered * weights[..., None].astype(expert_out.dtype)).sum(1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def moe_dispatch(tokens, eidx, sidx, weights, E, C, bt=DEFAULT_BT,
                 bc=DEFAULT_BC, interpret=None):
    """Route tokens to [E, C, M] expert buffers.

    eidx/sidx: [T, K] int32 expert id and capacity slot per choice (use
    slot >= C to drop a choice); weights: [T, K] scale per choice (1.0 for
    plain dispatch)."""
    interpret = _resolve_interpret(interpret)
    return _dispatch_raw(tokens, eidx, sidx, weights, E, C, bt, bc,
                         interpret)


def _moe_dispatch_fwd(tokens, eidx, sidx, weights, E, C, bt, bc, interpret):
    interpret = _resolve_interpret(interpret)
    out = _dispatch_raw(tokens, eidx, sidx, weights, E, C, bt, bc,
                        interpret)
    return out, (tokens, eidx, sidx, weights)


def _moe_dispatch_bwd(E, C, bt, bc, interpret, res, g):
    tokens, eidx, sidx, weights = res
    interpret = _resolve_interpret(interpret)
    # d tokens[t] = sum_k w[t,k] * g[e_k, s_k] — a combine of g
    safe_s = jnp.minimum(sidx, C - 1)
    valid = (sidx < C).astype(weights.dtype)
    dtok = _combine_raw(g, eidx, safe_s, weights * valid, bt,
                        DEFAULT_BC, interpret).astype(tokens.dtype)
    # d weights[t,k] = g[e_k, s_k] . tokens[t]
    gathered = g[eidx, safe_s].astype(jnp.float32)  # [T, K, M]
    dw = (gathered * tokens[:, None, :].astype(jnp.float32)).sum(-1)
    dw = (dw * valid.astype(jnp.float32)).astype(weights.dtype)
    return dtok, None, None, dw


moe_dispatch.defvjp(_moe_dispatch_fwd, _moe_dispatch_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def moe_combine(expert_out, eidx, sidx, weights, bt=DEFAULT_BT,
                bj=DEFAULT_BC, interpret=None):
    """Gather expert outputs back per token: out[t] = sum_k w[t,k] *
    expert_out[e_k, s_k].  Dropped choices (slot >= C) contribute 0."""
    interpret = _resolve_interpret(interpret)
    C = expert_out.shape[1]
    safe_s = jnp.minimum(sidx, C - 1)
    valid = (sidx < C).astype(weights.dtype)
    return _combine_raw(expert_out, eidx, safe_s, weights * valid, bt, bj,
                        interpret)


def _moe_combine_fwd(expert_out, eidx, sidx, weights, bt, bj, interpret):
    out = moe_combine(expert_out, eidx, sidx, weights, bt, bj, interpret)
    return out, (expert_out, eidx, sidx, weights)


def _moe_combine_bwd(bt, bj, interpret, res, g):
    expert_out, eidx, sidx, weights = res
    interpret = _resolve_interpret(interpret)
    E, C, M = expert_out.shape
    safe_s = jnp.minimum(sidx, C - 1)
    valid = (sidx < C).astype(weights.dtype)
    d_eo = _dispatch_raw(g, eidx, safe_s, weights * valid, E, C, bt,
                         DEFAULT_BC, interpret).astype(expert_out.dtype)
    gathered = expert_out[eidx, safe_s].astype(jnp.float32)
    dw = (gathered * g[:, None, :].astype(jnp.float32)).sum(-1)
    dw = (dw * valid.astype(jnp.float32)).astype(weights.dtype)
    return d_eo, None, None, dw


moe_combine.defvjp(_moe_combine_fwd, _moe_combine_bwd)
