"""Fused paged-attention decode kernel (Pallas) with an XLA fallback.

The serving decode hot path was four separate HBM round trips per
layer: rotate q/k (RoPE), scatter the new k/v into the block pool,
gather every sequence's blocks back out, then run masked softmax
attention over the gathered copy.  This module fuses the gather + q
RoPE + attention into ONE Pallas kernel: the block table rides in as a
scalar-prefetch operand, so each grid step DMAs exactly one KV block
straight from the pool — the gathered [B, L, H, D] context copy never
exists in HBM.

Flash-decoding split-K: the context pages are divided into
``num_splits`` independent chunks.  Each (batch, split) cell produces
an UNNORMALIZED partial — running max ``m``, exp-sum ``l`` and
accumulator ``acc`` — and the chunks are combined afterwards with the
standard log-sum-exp merge.  Splits are parallel grid cells, so one
128k-context straggler occupies ``num_splits`` cells instead of
serializing its whole context behind everyone else's decode step.

Numerics contract: ``_xla_partials`` + ``_combine_splits`` is the
SAME split-K math in plain XLA ops (identical masking semantics, f32
accumulation, identical combine code object).  On CPU the fused path
lowers through it, so tier-1 and the jaxpr audits cover the exact
fused-step math with no pallas_call in the program.  The unfused
reference (``paged_decode_reference``) reproduces models/llama.py's
scatter/gather path for parity tests.

``num_splits`` is autotuned (FLAGS_use_autotune) through
kernels/autotune keyed on (chip, head_dim, kv_block_size,
max_blocks_per_seq, dtype) and persisted to the JSON cache.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from .costs import KernelCost, register_kernel_cost
from .kv_quant import decode_codes, quantize_kv

KERNEL_NAME = "fused_paged_decode"
NEG_INF = -1e30
_LANES = 128


def _rotate_half(x, c, s):
    """Rotate-half RoPE, matching models/llama.py apply_rope: c/s carry
    the per-position cos/sin rows broadcast against x's last dim."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def _scatter_token(pool, new, block_table, positions):
    """Write one token per sequence into its pool slot — the T == 1
    case of models/llama.py's ``_scatter`` (same index math, same
    column clamp)."""
    nb, bs = pool.shape[0], pool.shape[1]
    nbs = block_table.shape[1]
    rows = jnp.arange(block_table.shape[0])
    col = jnp.minimum(positions // bs, nbs - 1)
    idx = block_table[rows, col] * bs + positions % bs          # [B]
    flat = pool.reshape(nb * bs, pool.shape[2], pool.shape[3])
    flat = flat.at[idx].set(new.astype(pool.dtype))
    return flat.reshape(pool.shape)


def _scatter_token_quant(pool, scales, new, block_table, positions,
                         scheme):
    """Quantize-at-write T == 1 scatter (kernels/kv_quant): int8 codes
    into the pool row, the row's absmax scale into the [nb, bs] f32
    sidecar — same index math and column clamp as ``_scatter_token``,
    all inside the traced step (no host sync, H106)."""
    nb, bs = pool.shape[0], pool.shape[1]
    nbs = block_table.shape[1]
    rows = jnp.arange(block_table.shape[0])
    col = jnp.minimum(positions // bs, nbs - 1)
    idx = block_table[rows, col] * bs + positions % bs          # [B]
    codes, sc = quantize_kv(new, scheme)            # [B,KVH,D], [B]
    flat = pool.reshape(nb * bs, pool.shape[2], pool.shape[3])
    flat = flat.at[idx].set(codes)
    sflat = scales.reshape(nb * bs).at[idx].set(sc)
    return flat.reshape(pool.shape), sflat.reshape(nb, bs)


# ---------------------------------------------------------------------------
# split-K partials: Pallas kernel
# ---------------------------------------------------------------------------

def _decode_kernel(bt_ref, pos_ref, q_ref, cos_ref, sin_ref, k_ref, v_ref,
                   *rest, bs, pages_per_split, scale, kv_dtype=None):
    # quantized pools carry two extra per-block scale operands between
    # the KV refs and the outputs (same scalar-prefetch index map, so
    # each grid step DMAs its block's [bs] scale row alongside the
    # block itself)
    if kv_dtype is not None:
        (ks_ref, vs_ref, o_ref, m_out_ref, l_out_ref,
         qrot_ref, acc_ref, m_ref, l_ref) = rest
    else:
        (o_ref, m_out_ref, l_out_ref,
         qrot_ref, acc_ref, m_ref, l_ref) = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    s = pl.program_id(1)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        # rotate + pre-scale q once per (batch, split) cell: RoPE lives
        # inside the kernel, and folding 1/sqrt(D) into q here keeps the
        # score math a bare dot
        qv = q_ref[0].astype(jnp.float32)               # [KVH, rep, D]
        c = cos_ref[0].astype(jnp.float32)              # [half]
        sn = sin_ref[0].astype(jnp.float32)
        qrot_ref[:] = _rotate_half(qv, c, sn) * scale
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # one gathered KV block: [bs, KVH, D] -> [KVH, bs, D].  Quantized
    # pools dequant HERE, at the DMA boundary — codes * per-row scale
    # in f32, so the wide KV copy never exists in HBM (ISSUE 20)
    kq, vq = k_ref[0], v_ref[0]
    if kv_dtype is not None:
        kq = decode_codes(kq, kv_dtype) * ks_ref[0][:, None, None]
        vq = decode_codes(vq, kv_dtype) * vs_ref[0][:, None, None]
    kb = jnp.swapaxes(kq.astype(jnp.float32), 0, 1)
    vb = jnp.swapaxes(vq.astype(jnp.float32), 0, 1)

    scores = jax.lax.dot_general(
        qrot_ref[:], kb, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)             # [KVH, rep, bs]

    page = s * pages_per_split + p                      # logical page
    k_pos = page * bs + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 2)
    scores = jnp.where(k_pos <= pos_ref[b], scores, NEG_INF)

    m_cur = jnp.max(scores, axis=-1, keepdims=True)     # [KVH, rep, 1]
    m_new = jnp.maximum(m_ref[:], m_cur)
    alpha = jnp.exp(m_ref[:] - m_new)
    pexp = jnp.exp(scores - m_new)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        pexp, vb, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)             # [KVH, rep, D]
    l_ref[:] = l_ref[:] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
    m_ref[:] = m_new

    @pl.when(p == pages_per_split - 1)
    def _emit():
        o_ref[0, 0] = acc_ref[:]
        # per-row scalars broadcast over the lane dim (flash kernel lse
        # idiom: a 1-wide trailing dim is not a legal TPU output tile)
        m_out_ref[0, 0] = jnp.broadcast_to(m_ref[:], m_out_ref.shape[2:])
        l_out_ref[0, 0] = jnp.broadcast_to(l_ref[:], l_out_ref.shape[2:])


def _pallas_partials(q_rot_unused, q, cos_b, sin_b, k_pool, v_pool,
                     block_table, positions, num_splits, scale, interpret,
                     k_scale=None, v_scale=None, kv_dtype=None):
    """q: UNROTATED [B, KVH, rep, D]; returns (acc [B,S,KVH,rep,D] f32,
    m [B,S,KVH,rep] f32, l [B,S,KVH,rep] f32)."""
    B, KVH, rep, D = q.shape
    bs = k_pool.shape[1]
    nbs = block_table.shape[1]
    P = nbs // num_splits
    half = D // 2

    in_specs = [
        pl.BlockSpec((1, KVH, rep, D),
                     lambda b, s, p, bt, pos: (b, 0, 0, 0)),
        pl.BlockSpec((1, half), lambda b, s, p, bt, pos: (b, 0)),
        pl.BlockSpec((1, half), lambda b, s, p, bt, pos: (b, 0)),
        pl.BlockSpec((1, bs, KVH, D),
                     lambda b, s, p, bt, pos, _P=P:
                     (bt[b, s * _P + p], 0, 0, 0)),
        pl.BlockSpec((1, bs, KVH, D),
                     lambda b, s, p, bt, pos, _P=P:
                     (bt[b, s * _P + p], 0, 0, 0)),
    ]
    operands = [q, cos_b, sin_b, k_pool, v_pool]
    if kv_dtype is not None:
        # per-block scale rows ride the SAME block-table index map as
        # their blocks — one [bs] f32 row per DMA'd block
        in_specs += [
            pl.BlockSpec((1, bs), lambda b, s, p, bt, pos, _P=P:
                         (bt[b, s * _P + p], 0)),
            pl.BlockSpec((1, bs), lambda b, s, p, bt, pos, _P=P:
                         (bt[b, s * _P + p], 0)),
        ]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, num_splits, P),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, KVH, rep, D),
                         lambda b, s, p, bt, pos: (b, s, 0, 0, 0)),
            pl.BlockSpec((1, 1, KVH, rep, _LANES),
                         lambda b, s, p, bt, pos: (b, s, 0, 0, 0)),
            pl.BlockSpec((1, 1, KVH, rep, _LANES),
                         lambda b, s, p, bt, pos: (b, s, 0, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((KVH, rep, D), jnp.float32),
            pltpu.VMEM((KVH, rep, D), jnp.float32),
            pltpu.VMEM((KVH, rep, 1), jnp.float32),
            pltpu.VMEM((KVH, rep, 1), jnp.float32),
        ],
    )
    L = nbs * bs
    H = KVH * rep
    esize = jnp.dtype(k_pool.dtype).itemsize
    # quantized pools also stream one f32 scale per (pool, token) row
    scale_bytes = 2.0 * B * L * 4 if kv_dtype is not None else 0.0
    acc, m_b, l_b = pl.pallas_call(
        functools.partial(_decode_kernel, bs=bs, pages_per_split=P,
                          scale=scale, kv_dtype=kv_dtype),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, num_splits, KVH, rep, D),
                                 jnp.float32),
            jax.ShapeDtypeStruct((B, num_splits, KVH, rep, _LANES),
                                 jnp.float32),
            jax.ShapeDtypeStruct((B, num_splits, KVH, rep, _LANES),
                                 jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not interpret) else None,
        cost_estimate=pl.CostEstimate(
            flops=4.0 * B * H * D * L,
            bytes_accessed=float(2 * B * L * KVH * D * esize
                                 + scale_bytes),
            transcendentals=float(B * H * L)),
        interpret=interpret,
        name=KERNEL_NAME,
    )(block_table, positions, *operands)
    return acc, m_b[..., 0], l_b[..., 0]


# ---------------------------------------------------------------------------
# split-K partials: numerically-identical XLA lowering
# ---------------------------------------------------------------------------

def _xla_partials(q_rot, k_pool, v_pool, block_table, positions,
                  num_splits, k_scale=None, v_scale=None, kv_dtype=None):
    """Same split-K partials in plain XLA: q_rot is the ROTATED and
    pre-scaled [B, KVH, rep, D] f32 query (scale folded in, exactly as
    the kernel does at p == 0).  Quantized pools dequant at the gather
    with the IDENTICAL codes * per-row-scale f32 multiply the kernel
    fuses into its block DMA, so CPU covers the exact served math."""
    B = q_rot.shape[0]
    bs = k_pool.shape[1]
    nbs = block_table.shape[1]
    Lp = (nbs // num_splits) * bs                       # keys per split
    if kv_dtype is not None:
        kb = decode_codes(k_pool[block_table], kv_dtype) \
            * k_scale[block_table][..., None, None]     # [B,nbs,bs,KVH,D]
        vb = decode_codes(v_pool[block_table], kv_dtype) \
            * v_scale[block_table][..., None, None]
    else:
        kb = k_pool[block_table].astype(jnp.float32)    # [B,nbs,bs,KVH,D]
        vb = v_pool[block_table].astype(jnp.float32)
    kb = kb.reshape(B, num_splits, Lp, kb.shape[3], kb.shape[4])
    vb = vb.reshape(B, num_splits, Lp, vb.shape[3], vb.shape[4])
    scores = jnp.einsum("bkrd,bslkd->bskrl", q_rot, kb,
                        preferred_element_type=jnp.float32)
    k_pos = jnp.arange(nbs * bs).reshape(num_splits, Lp)
    valid = k_pos[None, :, None, None, :] <= \
        positions[:, None, None, None, None]
    scores = jnp.where(valid, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                        # [B,S,KVH,rep]
    pexp = jnp.exp(scores - m[..., None])
    l = jnp.sum(pexp, axis=-1)
    acc = jnp.einsum("bskrl,bslkd->bskrd", pexp, vb,
                     preferred_element_type=jnp.float32)
    return acc, m, l


def _combine_splits(acc, m, l):
    """Log-sum-exp merge of the per-split partials — shared verbatim by
    both lowerings, so the combine rounding is identical."""
    m_g = jnp.max(m, axis=1)                            # [B,KVH,rep]
    w = jnp.exp(m - m_g[:, None])                       # [B,S,KVH,rep]
    l_g = jnp.sum(w * l, axis=1)
    out = jnp.sum(w[..., None] * acc, axis=1)
    return out / jnp.maximum(l_g, 1e-30)[..., None]     # [B,KVH,rep,D]


# ---------------------------------------------------------------------------
# autotuning
# ---------------------------------------------------------------------------

def _split_candidates(nbs):
    return [s for s in (1, 2, 4, 8, 16) if s <= nbs and nbs % s == 0]


def _default_splits(nbs):
    """Static heuristic: ~4-way split-K once the table is deep enough
    to amortize the combine, else fewer."""
    best = 1
    for s in _split_candidates(nbs):
        if s <= max(1, nbs // 2) and s <= 4:
            best = s
    return best


def _autotuned_splits(q, k_pool, block_table, interpret):
    """num_splits via the autotune cache (FLAGS_use_autotune), keyed on
    (chip, head_dim, kv_block_size, max_blocks_per_seq, dtype) — chip
    is stamped into the key by kernels/autotune itself."""
    from ..core.flags import flag
    from . import autotune as at

    nbs = block_table.shape[1]
    if not flag("use_autotune"):
        return _default_splits(nbs)
    D = q.shape[-1]
    bs = k_pool.shape[1]
    key = (D, bs, nbs, str(k_pool.dtype))
    if isinstance(q, jax.core.Tracer):
        hit = at.lookup("paged_attn_decode", key)
        return hit[0] if hit else _default_splits(nbs)
    cands = _split_candidates(nbs)
    if len(cands) == 1:
        return cands[0]

    jitted = {}

    def run(cfg):
        fn = jitted.get(cfg)
        if fn is None:
            fn = jax.jit(functools.partial(
                fused_paged_decode, num_splits=cfg[0],
                interpret=interpret))
            jitted[cfg] = fn
        out, kp, vp = fn(*_autotune_args)
        jax.block_until_ready(out)

    # the eager caller's actual operands double as the timing workload
    _autotune_args = _AUTOTUNE_OPERANDS.get("args")
    if _autotune_args is None:
        return _default_splits(nbs)
    best = at.autotune("paged_attn_decode", key,
                       [(s,) for s in cands], run)
    return best[0] if best else _default_splits(nbs)


_AUTOTUNE_OPERANDS: dict = {}


def autotune_paged_decode(q, k_new, v_new, k_pool, v_pool, block_table,
                          positions, cos, sin):
    """Eagerly search num_splits for these operand shapes and persist
    the winner (bench.py / warmup entry point — under a jit trace the
    kernel can only LOOK UP a previously-persisted winner)."""
    _AUTOTUNE_OPERANDS["args"] = (q, k_new, v_new, k_pool, v_pool,
                                  block_table, positions, cos, sin)
    try:
        return _autotuned_splits(q, k_pool, block_table,
                                 jax.default_backend() != "tpu")
    finally:
        _AUTOTUNE_OPERANDS.clear()


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def fused_paged_decode(q, k_new, v_new, k_pool, v_pool, block_table,
                       positions, cos, sin, *, num_splits=None,
                       use_pallas=None, interpret=None,
                       k_scale=None, v_scale=None, kv_cache_dtype=None):
    """One fused decode step of paged attention.

    q: [B, 1, H, D] UNROTATED queries; k_new/v_new: [B, 1, KVH, D]
    unrotated new-token key/value; k_pool/v_pool: [nb, bs, KVH, D]
    block pools; block_table: [B, max_blocks] int32; positions: [B]
    int32 per-sequence write frontiers; cos/sin: [max_pos, D/2] RoPE
    tables.  Returns (attn_out [B, 1, H, D], new_k_pool, new_v_pool).

    RoPE is applied to q and k_new at ``positions[b]``, the rotated
    k/v are scattered into the pools, and attention runs over the
    updated pools through the block table with causal masking
    ``k_pos <= positions[b]`` (garbage-block-0 rows sit past the
    frontier and are masked off).  On TPU the gather + q-RoPE +
    attention is one Pallas kernel; elsewhere the numerically-identical
    XLA split-K lowering runs instead.

    Quantized pools (``kv_cache_dtype`` = ``"int8"``/``"fp8"``,
    kernels/kv_quant): ``k_pool``/``v_pool`` hold int8 codes and
    ``k_scale``/``v_scale`` the [nb, bs] per-row f32 absmax scales.
    The new token quantizes at write and dequant fuses into the block
    DMA; the return grows to (attn_out, new_k_pool, new_v_pool,
    new_k_scale, new_v_scale).
    """
    from ..core.flags import flag

    B, T, H, D = q.shape
    if T != 1:
        raise ValueError(f"fused_paged_decode is single-token (T == 1), "
                         f"got T == {T}")
    KVH = k_new.shape[2]
    rep = H // KVH
    nbs = block_table.shape[1]
    positions = jnp.asarray(positions, jnp.int32)
    scale = 1.0 / math.sqrt(D)

    from .fusion import pallas_interpret_forced

    if use_pallas is None:
        if pallas_interpret_forced() and _HAS_PLTPU:
            use_pallas, interpret = True, True
        else:
            use_pallas = bool(flag("use_pallas_kernels")) and \
                jax.default_backend() == "tpu" and _HAS_PLTPU
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if num_splits is None:
        num_splits = _autotuned_splits(q, k_pool, block_table, interpret)
    if nbs % num_splits:
        num_splits = _default_splits(nbs)

    # per-sequence RoPE rows + scatter of the rotated new token (tiny:
    # B rows — XLA prologue shared verbatim by both lowerings).  A
    # quantized pool quantizes the token's row here, at write time,
    # inside the traced step.
    c = cos[positions]                                  # [B, half] f32
    s = sin[positions]
    k_rot = _rotate_half(k_new[:, 0].astype(jnp.float32),
                         c[:, None, :], s[:, None, :]).astype(k_new.dtype)
    if kv_cache_dtype is not None:
        new_k_pool, new_k_scale = _scatter_token_quant(
            k_pool, k_scale, k_rot, block_table, positions,
            kv_cache_dtype)
        new_v_pool, new_v_scale = _scatter_token_quant(
            v_pool, v_scale, v_new[:, 0], block_table, positions,
            kv_cache_dtype)
    else:
        new_k_pool = _scatter_token(k_pool, k_rot, block_table, positions)
        new_v_pool = _scatter_token(v_pool, v_new[:, 0], block_table,
                                    positions)
        new_k_scale = new_v_scale = None

    q_g = q[:, 0].reshape(B, KVH, rep, D)               # GQA grouping
    if use_pallas:
        acc, m, l = _pallas_partials(
            None, q_g, c, s, new_k_pool, new_v_pool, block_table,
            positions, num_splits, scale, interpret,
            k_scale=new_k_scale, v_scale=new_v_scale,
            kv_dtype=kv_cache_dtype)
    else:
        q_rot = _rotate_half(q_g.astype(jnp.float32),
                             c[:, None, None, :],
                             s[:, None, None, :]) * scale
        acc, m, l = _xla_partials(q_rot, new_k_pool, new_v_pool,
                                  block_table, positions, num_splits,
                                  k_scale=new_k_scale,
                                  v_scale=new_v_scale,
                                  kv_dtype=kv_cache_dtype)
    out = _combine_splits(acc, m, l)                    # [B,KVH,rep,D]
    out = out.reshape(B, 1, H, D).astype(q.dtype)
    if kv_cache_dtype is not None:
        return out, new_k_pool, new_v_pool, new_k_scale, new_v_scale
    return out, new_k_pool, new_v_pool


def paged_decode_reference(q, k_new, v_new, k_pool, v_pool, block_table,
                           positions, cos, sin, *, k_scale=None,
                           v_scale=None, kv_cache_dtype=None):
    """The UNFUSED scatter/gather decode math of models/llama.py's
    paged branch (rope gather path, full-buffer masked softmax) — the
    parity oracle for both fused lowerings.  With a quantized pool it
    quantizes the write and dequantizes the WHOLE gathered view up
    front (the naive two-pass the fused path avoids)."""
    B, T, H, D = q.shape
    positions = jnp.asarray(positions, jnp.int32)
    pos = positions[:, None] + jnp.arange(T)            # [B, 1]
    c = cos[pos][:, :, None, :]
    s = sin[pos][:, :, None, :]
    q_r = _rotate_half(q.astype(jnp.float32), c, s).astype(q.dtype)
    k_r = _rotate_half(k_new.astype(jnp.float32), c, s).astype(k_new.dtype)
    if kv_cache_dtype is not None:
        kp, ks = _scatter_token_quant(k_pool, k_scale, k_r[:, 0],
                                      block_table, positions,
                                      kv_cache_dtype)
        vp, vs = _scatter_token_quant(v_pool, v_scale, v_new[:, 0],
                                      block_table, positions,
                                      kv_cache_dtype)
        kd = decode_codes(kp, kv_cache_dtype) * ks[:, :, None, None]
        vd = decode_codes(vp, kv_cache_dtype) * vs[:, :, None, None]
    else:
        kp = _scatter_token(k_pool, k_r[:, 0], block_table, positions)
        vp = _scatter_token(v_pool, v_new[:, 0], block_table, positions)
        kd, vd = kp, vp
    kb = kd[block_table].reshape(B, -1, kp.shape[2], kp.shape[3])
    vb = vd[block_table].reshape(B, -1, vp.shape[2], vp.shape[3])
    rep = H // kb.shape[2]
    if rep > 1:
        kb = jnp.repeat(kb, rep, axis=2)
        vb = jnp.repeat(vb, rep, axis=2)
    scores = jnp.einsum("bthd,bshd->bhts", q_r, kb,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(D)
    k_pos = jnp.arange(kb.shape[1])
    valid = k_pos[None, None, :] <= pos[:, :, None]
    scores = jnp.where(valid[:, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhts,bshd->bthd", probs, vb)
    return out, kp, vp


# ---------------------------------------------------------------------------
# cost annotation (xray/shardplan price the pallas_call through this)
# ---------------------------------------------------------------------------

def _paged_decode_cost(in_avals, out_avals):
    # operand order fixed by _pallas_partials:
    # (block_table, positions, q, cos, sin, k_pool, v_pool
    #  [, k_scale, v_scale])  — the two trailing scale operands mark a
    # QUANTIZED pool (kernels/kv_quant), whose int8 element size flows
    # through ``esize`` below so the roofline prices quantized bytes
    bt_shape = in_avals[0][0]
    q_shape, q_dtype = in_avals[2][0], in_avals[2][1]
    pool_shape, pool_dtype = in_avals[5][0], in_avals[5][1]
    B, nbs = int(bt_shape[0]), int(bt_shape[1])
    KVH, rep, D = int(q_shape[1]), int(q_shape[2]), int(q_shape[3])
    bs = int(pool_shape[1])
    H, L = KVH * rep, nbs * bs
    flops = 4.0 * B * H * D * L                         # qk^T + pv MACs
    trans = float(B * H * L)                            # exp per score
    esize = np.dtype(pool_dtype).itemsize
    in_bytes = sum(
        float(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        for shape, dt in in_avals[:5])                  # q/rope/tables
    # the pools are read THROUGH the block table: B*L rows each, not
    # the whole pool allocation
    kv_bytes = 2.0 * B * L * KVH * D * esize
    if len(in_avals) > 7:                               # quantized pool
        # one f32 absmax per (pool, token) row streams with its block
        kv_bytes += 2.0 * B * L * np.dtype(in_avals[7][1]).itemsize
    out_bytes = sum(
        float(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        for shape, dt in out_avals)
    # compute dtype stays q's (the kernel dequantizes to f32 for the
    # dots); the QUANTIZED width is already priced into kv_bytes
    return KernelCost(flops=flops, bytes_accessed=in_bytes + kv_bytes
                      + out_bytes, transcendentals=trans,
                      dtype=str(q_dtype))


register_kernel_cost(
    KERNEL_NAME, _paged_decode_cost,
    sample_in=[((4, 8), "int32"), ((4,), "int32"),
               ((4, 2, 2, 16), "float32"), ((4, 8), "float32"),
               ((4, 8), "float32"), ((32, 8, 2, 16), "float32"),
               ((32, 8, 2, 16), "float32")],
    sample_out=[((4, 2, 2, 2, 16), "float32"),
                ((4, 2, 2, 2, 128), "float32"),
                ((4, 2, 2, 2, 128), "float32")])
