# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.fft (reference: python/paddle/fft.py, kernels via Pocketfft/cuFFT;
here all transforms lower to XLA FFT)."""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply
from .core.tensor import Tensor, to_tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
           "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "fftfreq",
           "rfftfreq", "fftshift", "ifftshift"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _norm(norm):
    return norm if norm in ("ortho", "forward") else "backward"


def _wrap1(name, fn):
    def op(x, n=None, axis=-1, norm="backward", name_arg=None):
        return apply(name, lambda v: fn(v, n=n, axis=axis, norm=_norm(norm)),
                     _t(x))
    op.__name__ = name
    return op


def _wrap2(name, fn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name_arg=None):
        return apply(name, lambda v: fn(v, s=s, axes=tuple(axes),
                                        norm=_norm(norm)), _t(x))
    op.__name__ = name
    return op


def _wrapn(name, fn):
    def op(x, s=None, axes=None, norm="backward", name_arg=None):
        return apply(name, lambda v: fn(
            v, s=s, axes=tuple(axes) if axes is not None else None,
            norm=_norm(norm)), _t(x))
    op.__name__ = name
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)
fft2 = _wrap2("fft2", jnp.fft.fft2)
ifft2 = _wrap2("ifft2", jnp.fft.ifft2)
rfft2 = _wrap2("rfft2", jnp.fft.rfft2)
irfft2 = _wrap2("irfft2", jnp.fft.irfft2)
fftn = _wrapn("fftn", jnp.fft.fftn)
ifftn = _wrapn("ifftn", jnp.fft.ifftn)
rfftn = _wrapn("rfftn", jnp.fft.rfftn)
irfftn = _wrapn("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply("fftshift", lambda v: jnp.fft.fftshift(v, axes=axes), _t(x))


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift", lambda v: jnp.fft.ifftshift(v, axes=axes), _t(x))


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D FFT of a hermitian-symmetric signal (reference: paddle.fft.hfft2
    — real output)."""
    return hfftn(x, s=s, axes=axes, norm=norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    def _fn(v):
        # hermitian n-d = fft over leading axes then hfft on the last;
        # with axes=None, `s` applies to the LAST len(s) dims (numpy
        # semantics), not to all of them
        if axes is not None:
            ax = tuple(axes)
        elif s is not None:
            ax = tuple(range(-len(s), 0))
        else:
            ax = tuple(range(-v.ndim, 0))
        out = v
        for i, a in enumerate(ax[:-1]):
            out = jnp.fft.fft(out, n=None if s is None else s[i],
                              axis=a, norm=norm)
        n_last = None if s is None else s[-1]
        return jnp.fft.hfft(out, n=n_last, axis=ax[-1], norm=norm)

    return apply("hfftn", _fn, _t(x))


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    def _fn(v):
        if axes is not None:
            ax = tuple(axes)
        elif s is not None:
            ax = tuple(range(-len(s), 0))
        else:
            ax = tuple(range(-v.ndim, 0))
        n_last = None if s is None else s[-1]
        out = jnp.fft.ihfft(v, n=n_last, axis=ax[-1], norm=norm)
        for i, a in enumerate(ax[:-1]):
            out = jnp.fft.ifft(out, n=None if s is None else s[i],
                               axis=a, norm=norm)
        return out

    return apply("ihfftn", _fn, _t(x))
