# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle_tpu — a TPU-native deep learning framework.

Brand-new framework with the capabilities of the PaddlePaddle reference
(surveyed in /root/repo/SURVEY.md), designed TPU-first: eager execution and
autograd over functional JAX/XLA computations, jit compilation of whole
training steps, GSPMD sharding over device meshes instead of NCCL process
groups, and Pallas kernels for fused hot ops.

Public surface mirrors `paddle.*` so reference users can migrate:
    import paddle_tpu as paddle
"""
from __future__ import annotations

__version__ = "0.1.0"


class version:
    """paddle.version namespace (reference: generated python/paddle/version.py)."""

    full_version = "0.1.0"
    major, minor, patch = "0", "1", "0"
    rc = "0"
    cuda_version = "None"  # TPU build
    cudnn_version = "None"

    @staticmethod
    def show():
        print(f"paddle_tpu {version.full_version} (TPU/XLA build)")

    @staticmethod
    def cuda():
        return None

    @staticmethod
    def cudnn():
        return None

# --- core types -----------------------------------------------------------
from .core.dtype import (  # noqa: F401
    DType, bfloat16, bool_, complex64, complex128, float16, float32, float64,
    float8_e4m3fn, get_default_dtype, int8, int16, int32, int64,
    set_default_dtype, uint8,
)
from .core.dtype import bool_ as bool  # noqa: F401  (paddle.bool)
from .core.place import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_cuda,
    is_compiled_with_tpu, set_device,
)
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
from .core.dispatch import no_grad, set_grad_enabled, is_grad_enabled  # noqa: F401

# --- ops ------------------------------------------------------------------
from . import ops as _ops_pkg

_ops_pkg.monkey_patch()

from .ops import *  # noqa: F401,F403
from .ops.random import seed, get_rng_state, set_rng_state  # noqa: F401
from .ops.random import check_shape  # noqa: F401  (reference: paddle.check_shape)

# --- subsystems (grown as they land; see SURVEY.md §7 layer order) --------
# observability first: pure stdlib, no framework imports, and every
# later subsystem may mirror metrics into it
from . import observability  # noqa: F401
from . import autograd  # noqa: F401
from .autograd import grad  # noqa: F401
from . import nn  # noqa: F401
from .nn.layer.layers import Layer, ParamAttr  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import kernels  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import framework  # noqa: F401
from .framework.io import load, save  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import vision  # noqa: F401
from . import device  # noqa: F401
from . import distributed  # noqa: F401
from . import hapi  # noqa: F401
from .hapi.model import Model, summary  # noqa: F401
from .hapi import callbacks  # noqa: F401
from .hapi import hub  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from . import distribution  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import sparse  # noqa: F401
from . import profiler  # noqa: F401
from . import inference  # noqa: F401
from . import incubate  # noqa: F401
from . import cost_model  # noqa: F401
from . import text  # noqa: F401
from . import onnx  # noqa: F401
from . import utils  # noqa: F401
from . import quantization  # noqa: F401
from .nn import utils as _nn_utils  # noqa: F401
from .models import bert as _bert_models  # noqa: F401
from . import models  # noqa: F401
from . import serving  # noqa: F401
from . import resilience  # noqa: F401

# paddle.linalg namespace is the ops.linalg module re-exported; register
# it in sys.modules so `import paddle_tpu.linalg` works like the reference
# `import paddle.linalg` (a real module there).
import sys as _sys

from .ops import linalg  # noqa: F401

_sys.modules.setdefault(__name__ + ".linalg", linalg)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor repr printing options (reference:
    python/paddle/tensor/to_string.py set_printoptions) — host-side, maps
    onto numpy's printoptions since Tensor.__repr__ renders via numpy."""
    import numpy as _np

    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def disable_static(place=None):
    """Back to eager mode (the default)."""
    from .static import graph as _g

    _g.disable_static()
    return None


def enable_static():
    """Switch to static-graph mode: ops on static.data Variables record into
    the default Program; Executor.run compiles + executes (see static/graph.py)."""
    from .static import graph as _g

    _g.enable_static()


def in_dynamic_mode():
    from .core.dispatch import in_static_trace
    from .static import graph as _g

    return not in_static_trace() and not _g.in_static_mode()


def is_grad_enabled_():  # kept for parity with some callers
    return is_grad_enabled()


# --- migration/parity shims ------------------------------------------------
from .core.place import (  # noqa: F401
    CUDAPinnedPlace, CUDAPlace, CustomPlace, IPUPlace, MLUPlace, NPUPlace,
    XPUPlace, is_compiled_with_cinn, is_compiled_with_ipu,
    is_compiled_with_mlu, is_compiled_with_npu, is_compiled_with_rocm,
    is_compiled_with_xpu,
)

# paddle.dtype: the scalar-type class itself (reference exposes VarType)
dtype = DType


def get_cudnn_version():
    """No cuDNN on TPU (reference: paddle.get_cudnn_version -> int|None)."""
    return None


def get_cuda_rng_state():
    """Maps onto the framework RNG state — there is one generator tree, not
    a CUDA-specific one (reference: python/paddle/framework/random.py)."""
    return get_rng_state()


def set_cuda_rng_state(state):
    return set_rng_state(state)


def disable_signal_handler():
    """Parity no-op: the reference unhooks its C++ fatal-signal dumper
    (paddle/fluid/platform/init.cc DisableSignalHandler); we install none."""
    return None


def batch(reader, batch_size, drop_last=False):
    """Reader decorator grouping samples into lists of `batch_size`
    (reference: python/paddle/batch.py)."""

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Top-level parameter factory (reference: python/paddle/tensor/creation.py
    create_parameter).  In static mode delegates to the Program; eagerly
    builds a Parameter initialized per `default_initializer` (default:
    zeros for bias-like, Xavier-uniform otherwise, matching the reference)."""
    from .static import graph as _g

    if _g.in_static_mode():
        return static.create_parameter(
            shape, dtype, name=name, initializer=default_initializer,
            is_bias=is_bias)
    import jax.numpy as _jnp

    from .core.dtype import to_np as _to_np
    from .nn import initializer as _I

    init = default_initializer
    if init is None:
        # same defaults as the static path (static/graph.py
        # create_parameter), so behavior doesn't depend on the mode
        init = _I.Constant(0.0) if is_bias else _I.XavierUniform()
    p = Parameter(_jnp.zeros(tuple(int(s) for s in shape), _to_np(dtype)),
                  name=name)
    with no_grad():
        init(p)
    return p


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.dynamic_flops import flops as _flops

    return _flops(net, input_size, custom_ops=custom_ops,
                  print_detail=print_detail)


def tanh_(x):
    """In-place tanh, also exported at top level like the reference."""
    return x.tanh_()
