# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.metric (reference: python/paddle/metric/metrics.py:37 Metric base,
:180 Accuracy, Precision, Recall, Auc)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            if label_np.shape[-1] == pred_np.shape[-1] \
                    and label_np.shape[-1] > 1:
                label_np = label_np.argmax(-1)  # one-hot
            else:
                # [N, 1] integer labels (the reference's standard layout,
                # metrics.py:180): a trailing 1 is NOT one-hot — argmax
                # would flatten every label to class 0
                label_np = label_np[..., 0]
        correct = idx == label_np[..., None]
        return Tensor(jnp.asarray(correct.astype(np.float32)))

    def update(self, correct, *args):
        c = _np(correct)
        num_samples = c.shape[0]
        accs = []
        for k in self.topk:
            num_corrects = c[..., :k].sum()
            accs.append(float(num_corrects) / max(num_samples, 1))
            self.total[self.topk.index(k)] += num_corrects
            self.count[self.topk.index(k)] += num_samples
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(int).reshape(-1)
        l = _np(labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(int).reshape(-1)
        l = _np(labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args,
                 **kwargs):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(int), 0,
                       self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over threshold bins, descending threshold
        pos = self._stat_pos[::-1].cumsum()
        neg = self._stat_neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    from ..ops.math import accuracy as _acc

    return _acc(input, label, k)
