"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _axis(axis):
    if axis is None:
        return None
    return tuple(axis) if isinstance(axis, (list, tuple)) else int(axis)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("std",
                 lambda v: jnp.std(v, axis=_axis(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), _t(x))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("var",
                 lambda v: jnp.var(v, axis=_axis(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), _t(x))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def _median(v):
        if mode == "avg":
            return jnp.median(v, axis=_axis(axis), keepdims=keepdim)
        # min mode: lower of the two middle values
        ax = _axis(axis)
        if ax is None:
            flat = jnp.sort(v.reshape(-1))
            return flat[(flat.shape[0] - 1) // 2]
        srt = jnp.sort(v, axis=ax)
        idx = (srt.shape[ax] - 1) // 2
        out = jnp.take(srt, idx, axis=ax)
        return jnp.expand_dims(out, ax) if keepdim else out
    return apply("median", _median, _t(x))


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply("nanmedian",
                 lambda v: jnp.nanmedian(v, axis=_axis(axis), keepdims=keepdim), _t(x))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    def _q(v):
        qq = jnp.asarray(q)
        return jnp.quantile(v, qq, axis=_axis(axis), keepdims=keepdim,
                            method=interpolation)
    return apply("quantile", _q, _t(x))


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    def _q(v):
        return jnp.nanquantile(v, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim,
                               method=interpolation)
    return apply("nanquantile", _q, _t(x))


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, jnp.int64))


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    def _hist(v):
        lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
        counts, _ = jnp.histogram(v.reshape(-1), bins=bins, range=(lo, hi),
                                  density=density)
        return counts if density else counts.astype(jnp.int64)
    return apply("histogram", _hist, _t(input), _differentiable=False)


def bincount(x, weights=None, minlength=0, name=None):
    from ..core.dispatch import in_static_trace
    import numpy as np

    if in_static_trace():
        raise RuntimeError("bincount has data-dependent shape under jit")
    arr = np.asarray(x._value)
    w = np.asarray(weights._value) if isinstance(weights, Tensor) else weights
    return Tensor(jnp.asarray(np.bincount(arr, weights=w, minlength=minlength)))
