"""Einsum (reference: python/paddle/tensor/einsum.py) — delegates to XLA dot lowering."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor


def einsum(equation, *operands):
    ops = [o if isinstance(o, Tensor) else to_tensor(o) for o in operands]
    return apply("einsum", lambda *vs: jnp.einsum(equation, *vs), *ops)
