"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.dtype import get_default_dtype, to_np
from ..core.tensor import Tensor, to_tensor  # noqa: F401  (re-export)


def _np_dtype(dtype, default_float=True):
    if dtype is None:
        return to_np(get_default_dtype()) if default_float else None
    return to_np(dtype)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _np_dtype(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _np_dtype(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = "bool"
        elif isinstance(fill_value, int):
            dtype = get_default_dtype()
        else:
            dtype = get_default_dtype()
    return Tensor(jnp.full(_shape(shape), fill_value, to_np(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype, name)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def zeros_like(x, dtype=None, name=None):
    return apply("zeros_like", lambda v: jnp.zeros_like(v, dtype=to_np(dtype)), x,
                 _differentiable=False)


def ones_like(x, dtype=None, name=None):
    return apply("ones_like", lambda v: jnp.ones_like(v, dtype=to_np(dtype)), x,
                 _differentiable=False)


def full_like(x, fill_value, dtype=None, name=None):
    return apply("full_like",
                 lambda v: jnp.full_like(v, fill_value, dtype=to_np(dtype)), x,
                 _differentiable=False)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(a):
        return a.item() if isinstance(a, Tensor) else a
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = ("int64" if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
                 else get_default_dtype())
    return Tensor(jnp.arange(start, end, step, dtype=to_np(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(a):
        return a.item() if isinstance(a, Tensor) else a
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=_np_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(a):
        return a.item() if isinstance(a, Tensor) else a
    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)), base=_v(base),
                               dtype=_np_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_np_dtype(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def _diag(v):
        if v.ndim == 1:
            out = jnp.diag(v, k=offset)
            if padding_value != 0:
                mask = jnp.eye(out.shape[0], out.shape[1], k=offset, dtype=bool)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(v, offset=offset)
    return apply("diag", _diag, x)


def diagflat(x, offset=0, name=None):
    return apply("diagflat", lambda v: jnp.diagflat(v, k=offset), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    import numpy as _np

    def _embed(v):
        n = v.shape[-1] + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(v)
        if (dim1, dim2) != (-2, -1):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out
    return apply("diag_embed", _embed, x)


def tril(x, diagonal=0, name=None):
    return apply("tril", lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply("triu", lambda v: jnp.triu(v, k=diagonal), x)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=to_np(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=to_np(dtype)))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = apply("meshgrid", lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")), *args)
    return list(outs) if isinstance(outs, tuple) else [outs]


def assign(x, output=None):
    if not isinstance(x, Tensor):
        x = to_tensor(x)
    out = apply("assign", lambda v: v + 0, x)
    if output is not None:
        output._rebind(out)
        return output
    return out


def clone(x, name=None):
    return assign(x)


def complex(real, imag, name=None):
    return apply("complex", lambda r, i: jax.lax.complex(r, i), real, imag)


def one_hot(x, num_classes, name=None):
    import jax

    return apply("one_hot",
                 lambda v: jax.nn.one_hot(v, num_classes, dtype=to_np(get_default_dtype())),
                 x, _differentiable=False)
