"""Random ops + global RNG state.

The reference uses per-device stateful cuRAND generators
(/root/reference/python/paddle/fluid/framework.py seed handling,
paddle/phi/kernels gaussian kernels).  JAX randomness is functional; we keep a
paddle-style *stateful* facade: a global Generator holding a jax PRNG key that
splits on every draw.  Under a to_static trace, the key for each draw comes
from a trace-key provider (the traced program takes the step key as an input —
see paddle_tpu/jit/), so compiled programs get fresh randomness every step
without recompiling.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.dtype import get_default_dtype, to_np
from ..core.tensor import Tensor, to_tensor


class Generator:
    """Lazy PRNG state: the key materializes on first use, NOT at
    construction — creating it at import time would run a computation and
    poison jax.distributed.initialize (which must run before any)."""

    def __init__(self, seed: int = 0):
        self._key = None
        self._seed = seed
        self._counter = 0

    def manual_seed(self, seed: int):
        self._key = jax.random.PRNGKey(seed)
        self._seed = seed
        self._counter = 0
        return self

    def initial_seed(self):
        return self._seed

    def next_key(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    def next_key_data(self):
        """uint32[2] key data derived HOST-side (pure python/numpy, no
        traced op): splitmix64 of (seed, counter).  A plain seed-XOR-
        counter would make different seeds' key sequences permutations
        of one key set (seed 3 at step 1 == seed 0 at step 2); the
        splitmix finalizer decorrelates them.  Consumers hash the data
        again (threefry fold_in / random bits).  Used for the per-call
        step key of compiled programs, where an eager jax.random.split
        dominated the whole per-call host overhead (~78% measured)."""
        import numpy as np

        self._counter += 1
        mask = (1 << 64) - 1
        # splitmix64 finalizer over seed*golden ^ counter
        z = ((self._seed * 0x9E3779B97F4A7C15) ^ self._counter) & mask
        z = (z + 0x9E3779B97F4A7C15) & mask
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & mask
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & mask
        z ^= z >> 31
        return np.array([(z >> 32) & 0xFFFFFFFF, z & 0xFFFFFFFF],
                        np.uint32)


_default_generator = Generator(0)


class _TraceKeyState(threading.local):
    def __init__(self):
        self.provider = None  # callable () -> key, set during to_static traces


_trace_keys = _TraceKeyState()


def set_trace_key_provider(provider):
    prev = _trace_keys.provider
    _trace_keys.provider = provider
    return prev


def default_generator() -> Generator:
    return _default_generator


def next_key():
    if _trace_keys.provider is not None:
        return _trace_keys.provider()
    return _default_generator.next_key()


def seed(value: int):
    _default_generator.manual_seed(int(value))
    return _default_generator


def get_rng_state():
    g = _default_generator
    if g._key is None:
        g._key = jax.random.PRNGKey(g._seed)
    # element 0: the eager split-chain key (historic format, kept first
    # for compat); element 1: opaque (seed, counter) tuple driving
    # compiled-program step keys — omitting it silently broke replay of
    # to_static randomness after a restore
    return [jnp.asarray(g._key), (g._seed, g._counter)]


def set_rng_state(state):
    g = _default_generator
    legacy = True
    if isinstance(state, (list, tuple)):
        g._key = jnp.asarray(state[0])
        if len(state) > 1 and isinstance(state[1], (tuple, list)):
            g._seed, g._counter = int(state[1][0]), int(state[1][1])
            legacy = False
    else:
        g._key = jnp.asarray(state)
    if legacy:
        # a single-key (pre-r4) state carries no (seed, counter) pair:
        # reset the compiled-program chain DETERMINISTICALLY instead of
        # silently resuming from whatever counter this process had
        # (ADVICE r4 — compiled randomness would replay from the wrong
        # point); fold the restored key in so distinct states still
        # produce distinct compiled streams
        import numpy as _np

        g._seed = int(_np.asarray(g._key).ravel()[-1])
        g._counter = 0


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def _float_dtype(dtype):
    return to_np(dtype) if dtype is not None else to_np(get_default_dtype())


def rand(shape, dtype=None, name=None):
    key = next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _float_dtype(dtype)))


def randn(shape, dtype=None, name=None):
    key = next_key()
    return Tensor(jax.random.normal(key, _shape(shape), _float_dtype(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = next_key()
    def _v(a):
        return float(a.item()) if isinstance(a, Tensor) else float(a)
    return Tensor(jax.random.uniform(key, _shape(shape), _float_dtype(dtype),
                                     minval=_v(min), maxval=_v(max)))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    out = uniform(x.shape, x.dtype, min, max, seed)
    x._value = out._value
    x._version += 1
    return x


def normal(mean=0.0, std=1.0, shape=None, name=None):
    key = next_key()
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            jnp.shape(m), jnp.shape(s)) if shape is None else _shape(shape)
        return Tensor(jax.random.normal(key, shp, to_np(get_default_dtype())) * s + m)
    shp = _shape(shape) if shape is not None else ()
    return Tensor(jax.random.normal(key, shp, to_np(get_default_dtype())) * std + mean)


def normal_(x, mean=0.0, std=1.0, name=None):
    key = next_key()
    x._value = (jax.random.normal(key, tuple(x.shape), x._value.dtype) * std + mean)
    x._version += 1
    return x


def exponential_(x, lam=1.0, name=None):
    """In-place exponential(λ) fill (reference: paddle.Tensor.exponential_,
    python/paddle/tensor/random.py)."""
    key = next_key()
    x._value = jax.random.exponential(
        key, tuple(x.shape), x._value.dtype) / lam
    x._version += 1
    return x


def bernoulli_(x, p=0.5, name=None):
    key = next_key()
    x._value = jax.random.bernoulli(
        key, p, tuple(x.shape)).astype(x._value.dtype)
    x._version += 1
    return x


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = next_key()
    return Tensor(jax.random.normal(key, _shape(shape), _float_dtype(dtype)) * std + mean)


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    key = next_key()
    return Tensor(jax.random.randint(key, _shape(shape), low, high, to_np(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    key = next_key()
    dt = to_np(dtype) if dtype is not None else x._value.dtype
    return Tensor(jax.random.randint(key, tuple(x.shape), low, high, dt))


def randperm(n, dtype="int64", name=None):
    key = next_key()
    return Tensor(jax.random.permutation(key, n).astype(to_np(dtype)))


def shuffle(x, name=None):
    key = next_key()
    return apply("shuffle", lambda v: jax.random.permutation(key, v, axis=0,
                                                             independent=False), x)


def bernoulli(x, name=None):
    key = next_key()
    return apply("bernoulli",
                 lambda v: jax.random.bernoulli(key, v).astype(v.dtype), x,
                 _differentiable=False)


def bernoulli_(x, p=0.5, name=None):
    key = next_key()
    x._value = jax.random.bernoulli(key, p, tuple(x.shape)).astype(x._value.dtype)
    return x


def poisson(x, name=None):
    key = next_key()
    return apply("poisson",
                 lambda v: jax.random.poisson(key, v).astype(v.dtype), x,
                 _differentiable=False)


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = next_key()

    def _multinomial(v):
        logits = jnp.log(jnp.clip(v, 1e-30, None))
        if replacement:
            return jax.random.categorical(
                key, logits, axis=-1,
                shape=(num_samples,) + v.shape[:-1]).T.astype(jnp.int64) \
                if v.ndim > 1 else jax.random.categorical(
                    key, logits, shape=(num_samples,)).astype(jnp.int64)
        # without replacement: gumbel top-k trick
        g = jax.random.gumbel(key, v.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx.astype(jnp.int64)
    return apply("multinomial", _multinomial, x, _differentiable=False)


def exponential_(x, lam=1.0, name=None):
    key = next_key()
    x._value = jax.random.exponential(key, tuple(x.shape), x._value.dtype) / lam
    return x


def binomial(count, prob, name=None):
    key = next_key()

    def _binom(n, p):
        return jax.random.binomial(key, n, p).astype(jnp.int64)
    return apply("binomial", _binom, count, prob, _differentiable=False)


def rand_like(x, dtype=None, name=None):
    key = next_key()
    dt = to_np(dtype) if dtype is not None else x._value.dtype
    return Tensor(jax.random.uniform(key, tuple(x.shape), dt))


def randn_like(x, dtype=None, name=None):
    key = next_key()
    dt = to_np(dtype) if dtype is not None else x._value.dtype
    return Tensor(jax.random.normal(key, tuple(x.shape), dt))


def check_shape(shape, op_name="check_shape",
                expected_shape_type=(list, tuple),
                expected_element_type=(int,),
                expected_tensor_dtype=("int32", "int64")):
    """Validate a shape argument before it reaches a creation op
    (reference: fluid/data_feeder.py:152, exported as paddle.check_shape
    via tensor/random.py).  Accepts a list/tuple of non-negative ints
    (or int Tensors) or an int32/int64 shape Tensor."""
    from ..core.tensor import Tensor

    if isinstance(shape, Tensor):
        if str(shape.dtype).split(".")[-1] not in expected_tensor_dtype:
            raise TypeError(
                f"{op_name}: a shape Tensor must be "
                f"{'/'.join(expected_tensor_dtype)}, got {shape.dtype}")
        return
    if not isinstance(shape, expected_shape_type):
        raise TypeError(
            f"{op_name}: shape must be a list/tuple or int Tensor, "
            f"got {type(shape).__name__}")
    for ele in shape:
        if isinstance(ele, Tensor):
            if str(ele.dtype).split(".")[-1] not in expected_tensor_dtype:
                raise TypeError(
                    f"{op_name}: an element Tensor of shape must be "
                    f"{'/'.join(expected_tensor_dtype)}, got {ele.dtype}")
            continue
        if not isinstance(ele, expected_element_type) or isinstance(
                ele, bool):
            raise TypeError(
                f"{op_name}: all elements of shape must be integers, "
                f"got {ele!r}")
        if ele < 0:
            raise ValueError(
                f"{op_name}: all elements of shape must be non-negative "
                f"when given as a list/tuple, got {ele}")
