"""Op library: the paddle.tensor.* surface, dispatched through the tape.

monkey_patch() attaches operators and methods onto Tensor, mirroring the
reference's monkey-patching of ~400 tensor methods
(/root/reference/python/paddle/tensor/__init__.py tensor_method_func list).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_py_slice = slice  # builtin, captured before the paddle `slice` op shadows it
_py_all = all      # ditto for the paddle `all` reduction

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .random import (  # noqa: F401
    bernoulli, bernoulli_, exponential_, multinomial, normal, normal_,
    poisson, rand, randint, randint_like, randn, randperm, seed,
    standard_normal, uniform, uniform_, get_rng_state, set_rng_state,
    shuffle,
)
from .einsum import einsum  # noqa: F401

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor

from . import creation, logic, linalg, manipulation, math as math_mod, random  # noqa
from . import search, stat  # noqa


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _unwrap_index(idx):
    """Convert an indexing object possibly containing Tensors to raw values."""
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        vals = [_unwrap_index(i) for i in idx]
        # reference semantics: a list index is a FANCY index (gather) —
        # `x[[0, 2]]` selects rows 0 and 2.  jax rejects raw non-tuple
        # sequences, so materialize as an array.  Tensor/tracer elements
        # must STACK (np.asarray raises TracerArrayConversionError under
        # a trace, and a tuple fallback would silently turn the gather
        # into multi-axis indexing); only a list containing slices/
        # None/... falls back to tuple (numpy-deprecated form).
        if _py_all(v is not None and v is not Ellipsis
                   and not isinstance(v, _py_slice) for v in vals):
            try:
                return np.asarray(vals)
            except (ValueError, TypeError):
                return jnp.stack([jnp.asarray(v) for v in vals])
        return tuple(vals)
    if isinstance(idx, _py_slice):
        def iv(v):
            if isinstance(v, Tensor):
                return int(v.item())
            return v
        return _py_slice(iv(idx.start), iv(idx.stop), iv(idx.step))
    return idx


def _getitem(self, idx):
    raw_idx = _unwrap_index(idx)
    tensor_indices = [i for i in (idx if isinstance(idx, tuple) else (idx,))
                      if isinstance(i, Tensor)]
    # index tensors are non-differentiable closure constants
    return apply("getitem", lambda v: v[raw_idx], self)


def _setitem(self, idx, value):
    raw_idx = _unwrap_index(idx)
    v = _t(value) if not isinstance(value, (int, float, bool)) else value

    if isinstance(v, Tensor):
        out = apply("setitem",
                    lambda x, val: x.at[raw_idx].set(val.astype(x.dtype)), self, v)
    else:
        out = apply("setitem", lambda x: x.at[raw_idx].set(v), self)
    self._rebind(out)
    return self


_BINOPS = {
    "__add__": math_mod.add,
    "__radd__": lambda x, y: math_mod.add(_t(y), x),
    "__sub__": math_mod.subtract,
    "__rsub__": lambda x, y: math_mod.subtract(_t(y), x),
    "__mul__": math_mod.multiply,
    "__rmul__": lambda x, y: math_mod.multiply(_t(y), x),
    "__truediv__": math_mod.divide,
    "__rtruediv__": lambda x, y: math_mod.divide(_t(y), x),
    "__floordiv__": math_mod.floor_divide,
    "__rfloordiv__": lambda x, y: math_mod.floor_divide(_t(y), x),
    "__mod__": math_mod.mod,
    "__rmod__": lambda x, y: math_mod.mod(_t(y), x),
    "__pow__": math_mod.pow,
    "__rpow__": lambda x, y: math_mod.pow(_t(y), x),
    "__matmul__": math_mod.matmul,
    "__rmatmul__": lambda x, y: math_mod.matmul(_t(y), x),
    "__and__": logic.bitwise_and,
    "__or__": logic.bitwise_or,
    "__xor__": logic.bitwise_xor,
    "__eq__": logic.equal,
    "__ne__": logic.not_equal,
    "__lt__": logic.less_than,
    "__le__": logic.less_equal,
    "__gt__": logic.greater_than,
    "__ge__": logic.greater_equal,
}

# Methods delegating to module functions with self as first argument.
_METHODS = [
    # math
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "scale", "maximum", "minimum", "fmax", "fmin", "sqrt", "rsqrt", "exp",
    "expm1", "log", "log2", "log10", "log1p", "abs", "sign", "sin", "cos", "tan",
    "asin", "acos", "atan", "sinh", "cosh", "tanh", "floor", "ceil", "round",
    "trunc", "reciprocal", "square", "erf", "erfinv", "lgamma", "digamma",
    "angle", "conj", "clip", "lerp", "nan_to_num", "sum", "mean", "max", "min",
    "amax", "amin", "prod", "nansum", "nanmean", "logsumexp", "cumsum",
    "cumprod", "diff", "trace", "matmul", "mm", "bmm", "dot", "inner", "outer",
    "kron", "inverse", "isnan", "isinf", "isfinite", "sigmoid", "logit",
    "atan2", "heaviside", "deg2rad", "rad2deg", "diagonal", "frac",
    # manipulation
    "reshape", "reshape_", "flatten", "transpose", "t", "moveaxis", "swapaxes",
    "squeeze", "squeeze_", "unsqueeze", "unsqueeze_", "split", "chunk", "unbind",
    "tile", "expand", "expand_as", "broadcast_to", "flip", "roll", "rot90",
    "gather", "gather_nd", "take", "take_along_axis", "put_along_axis",
    "reverse",
    "scatter", "scatter_", "scatter_nd_add", "index_select", "index_sample",
    "index_add", "index_fill", "masked_select", "masked_fill", "unique", "pad",
    "repeat_interleave", "as_complex", "as_real", "cast", "view", "view_as",
    "tensordot", "where", "unfold", "as_strided", "vander", "trapezoid",
    "cumulative_trapezoid",
    # logic
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor", "isclose",
    "allclose", "equal_all", "any", "all",
    # linalg
    "norm", "dist", "cholesky", "matrix_power", "cross",
    # search
    "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
    "bucketize",
    # stat
    "std", "var", "median", "nanmedian", "quantile", "nanquantile", "histogram",
    "bincount",
    # random
    "bernoulli", "multinomial", "normal_", "uniform_", "bernoulli_",
    "exponential_",
]

_INPLACE_ALIASES = {
    "add_": math_mod.add, "subtract_": math_mod.subtract,
    "multiply_": math_mod.multiply, "divide_": math_mod.divide,
    "clip_": math_mod.clip, "scale_": math_mod.scale,
    "floor_": math_mod.floor, "ceil_": math_mod.ceil, "round_": math_mod.round,
    "exp_": math_mod.exp, "sqrt_": math_mod.sqrt, "rsqrt_": math_mod.rsqrt,
    "abs_": math_mod.abs, "tanh_": math_mod.tanh, "reciprocal_": math_mod.reciprocal,
    "neg_": math_mod.neg, "cast_": manipulation.cast,
    "flatten_": manipulation.flatten, "transpose_": manipulation.transpose,
    "lerp_": math_mod.lerp, "erfinv_": math_mod.erfinv,
    "put_along_axis_": manipulation.put_along_axis,
    "fill_diagonal_": None,  # handled separately below
}

_patched = False


def monkey_patch():
    global _patched
    if _patched:
        return
    _patched = True

    import sys

    mod = sys.modules[__name__]

    for name, fn in _BINOPS.items():
        setattr(Tensor, name, (lambda f: lambda self, other: f(self, other))(fn))
    Tensor.__hash__ = lambda self: id(self)
    Tensor.__neg__ = lambda self: math_mod.neg(self)
    Tensor.__abs__ = lambda self: math_mod.abs(self)
    Tensor.__invert__ = lambda self: logic.logical_not(self)
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem

    for name in _METHODS:
        fn = getattr(mod, name, None)
        if fn is None:
            continue
        if not hasattr(Tensor, name):
            setattr(Tensor, name, (lambda f: lambda self, *a, **k: f(self, *a, **k))(fn))

    for name, fn in _INPLACE_ALIASES.items():
        if fn is None:
            continue
        def make_inplace(f):
            def ip(self, *a, **k):
                return self._rebind(f(self, *a, **k))
            return ip
        setattr(Tensor, name, make_inplace(fn))

    def fill_diagonal_(self, value, offset=0, wrap=False):
        n = min(self.shape[-2], self.shape[-1])
        idx = jnp.arange(n - abs(offset))
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        self._value = self._value.at[..., r, c].set(value)
        self._version += 1
        return self

    Tensor.fill_diagonal_ = fill_diagonal_
    Tensor.T = property(lambda self: manipulation.transpose(
        self, list(range(self.ndim))[::-1]))
    Tensor.item_size = property(lambda self: self.dtype.itemsize)
