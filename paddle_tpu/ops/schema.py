"""Single-source op schema (reference: the api.yaml codegen pattern —
python/paddle/utils/code_gen/api.yaml + api_gen.py generate the typed C++
API, kernel dispatch, and eager forward functions from one declaration).

TPU-native inversion: kernels are XLA lowerings, so there is nothing to
codegen at build time — instead ONE yaml (`op_schema.yaml`) is the
authoritative registry of the public op surface, and code *validates
against* it:

- `get_op_info(name)` / `all_ops()` expose the registry at runtime
  (KernelFactory-style introspection).
- tests/test_op_schema.py is the API-freeze gate (reference:
  tools/check_api_compatible.py): an op vanishing, changing its
  signature, or appearing without a schema entry fails CI.

Regenerate after intentional surface changes with:
    python tools/gen_op_schema.py
(the diff then documents the API change for review, which is exactly how
the reference treats api.yaml edits).
"""
from __future__ import annotations

import dataclasses
import functools
import inspect
import os
from typing import Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class OpSpec:
    name: str
    module: str               # submodule within paddle_tpu.ops
    signature: str            # canonical "(x, y, name=None)" string
    is_method: bool           # exposed as a Tensor method
    inplace_variant: Optional[str]  # e.g. "add_" for "add"


_SCHEMA_PATH = os.path.join(os.path.dirname(__file__), "op_schema.yaml")


@functools.lru_cache(maxsize=1)
def _load() -> Dict[str, OpSpec]:
    import yaml

    with open(_SCHEMA_PATH) as f:
        raw = yaml.safe_load(f)
    out = {}
    for entry in raw["ops"]:
        spec = OpSpec(
            name=entry["op"],
            module=entry["module"],
            signature=entry["signature"],
            is_method=bool(entry.get("method", False)),
            inplace_variant=entry.get("inplace"),
        )
        out[spec.name] = spec
    return out


def all_ops() -> List[str]:
    return sorted(_load())


def get_op_info(name: str) -> OpSpec:
    try:
        return _load()[name]
    except KeyError:
        raise KeyError(f"no op schema entry for {name!r}") from None


def param_names(name: str) -> List[str]:
    """Ordered parameter names of an op's schema signature (``*``/``**``
    prefixes kept).  This is the same view `analysis.astlint` rule L002
    checks statically; exposing it here lets runtime tooling (and tests)
    compare a live callable against the frozen schema without string
    munging."""
    import ast

    sig = get_op_info(name).signature
    args = ast.parse(f"def _f{sig}: pass").body[0].args
    out = [a.arg for a in args.posonlyargs] + [a.arg for a in args.args]
    if args.vararg:
        out.append("*" + args.vararg.arg)
    out.extend(a.arg for a in args.kwonlyargs)
    if args.kwarg:
        out.append("**" + args.kwarg.arg)
    return out


def current_signature(fn) -> str:
    """Canonical signature string used by both generator and gate."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return "(...)"
    parts = []
    for p in sig.parameters.values():
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            parts.append(f"*{p.name}")
        elif p.kind == inspect.Parameter.VAR_KEYWORD:
            parts.append(f"**{p.name}")
        elif p.default is inspect.Parameter.empty:
            parts.append(p.name)
        else:
            parts.append(f"{p.name}={p.default!r}")
    return "(" + ", ".join(parts) + ")"
