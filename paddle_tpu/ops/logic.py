"""Comparison / logical / bitwise ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _cmp(op_name, fn):
    # public `name=None` kwarg must not shadow the dispatch name
    def op(x, y, name=None):
        return apply(op_name, fn, _t(x), _t(y), _differentiable=False)
    op.__name__ = op_name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)
bitwise_left_shift = _cmp("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _cmp("bitwise_right_shift", jnp.right_shift)


def logical_not(x, name=None):
    return apply("logical_not", jnp.logical_not, _t(x), _differentiable=False)


def bitwise_not(x, name=None):
    return apply("bitwise_not", jnp.bitwise_not, _t(x), _differentiable=False)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("isclose",
                 lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                          equal_nan=equal_nan),
                 _t(x), _t(y), _differentiable=False)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("allclose",
                 lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan),
                 _t(x), _t(y), _differentiable=False)


def equal_all(x, y, name=None):
    return apply("equal_all", lambda a, b: jnp.array_equal(a, b),
                 _t(x), _t(y), _differentiable=False)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def in_dynamic_mode():
    from ..core.dispatch import in_static_trace
    from ..static import graph as _g

    return not in_static_trace() and not _g.in_static_mode()


def any(x, axis=None, keepdim=False, name=None):
    def _axis(a):
        if a is None:
            return None
        return tuple(a) if isinstance(a, (list, tuple)) else int(a)
    return apply("any", lambda v: jnp.any(v, axis=_axis(axis), keepdims=keepdim),
                 _t(x), _differentiable=False)


def all(x, axis=None, keepdim=False, name=None):
    def _axis(a):
        if a is None:
            return None
        return tuple(a) if isinstance(a, (list, tuple)) else int(a)
    return apply("all", lambda v: jnp.all(v, axis=_axis(axis), keepdims=keepdim),
                 _t(x), _differentiable=False)


def is_complex(x):
    return jnp.issubdtype(_t(x)._value.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_t(x)._value.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_t(x)._value.dtype, jnp.integer)
