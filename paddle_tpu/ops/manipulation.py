"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py).

Ops whose output shape depends on data (nonzero, masked_select, unique) are
eager-only: XLA requires static shapes, so under a to_static trace they raise —
the reference has the same tension and resolves it with LoD/dynamic ops, we
resolve it by keeping them at the host boundary.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, in_static_trace
from ..core.dtype import to_np
from ..core.tensor import Tensor, to_tensor


py_slice = slice  # captured before the paddle-style `slice` op shadows it


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _static_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def cast(x, dtype):
    return apply("cast", lambda v: v.astype(to_np(dtype)), _t(x))


def _reshape_impl(v, shape=None):
    return jnp.reshape(v, shape)


def _reshape_rule(vals, attrs):
    (a,) = vals
    out = jnp.reshape(a, attrs["shape"])
    return out, lambda ct: (jnp.reshape(ct, a.shape).astype(a.dtype),)


def reshape(x, shape, name=None):
    shape = _static_shape(shape)
    return apply("reshape", _reshape_impl, _t(x), shape=tuple(shape))


def reshape_(x, shape, name=None):
    return x._rebind(reshape(x, shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def _flatten(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return jnp.reshape(v, new_shape)
    return apply("flatten", _flatten, _t(x))


def _transpose_impl(v, perm=None):
    return jnp.transpose(v, perm)


def _transpose_rule(vals, attrs):
    (a,) = vals
    perm = attrs.get("perm")
    out = jnp.transpose(a, perm)
    inv = (None if perm is None
           else tuple(int(i) for i in np.argsort(perm)))

    def vjp(ct):
        return (jnp.transpose(ct, inv).astype(a.dtype),)
    return out, vjp


def transpose(x, perm=None, name=None):
    if perm is not None:
        perm = tuple(int(p) for p in perm)
    return apply("transpose", _transpose_impl, _t(x), perm=perm)


def t(x, name=None):
    return apply("t", lambda v: v.T, _t(x))


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis", lambda v: jnp.moveaxis(v, source, destination), _t(x))


def swapaxes(x, axis1, axis2, name=None):
    return apply("swapaxes", lambda v: jnp.swapaxes(v, axis1, axis2), _t(x))


def squeeze(x, axis=None, name=None):
    def _squeeze(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(a % v.ndim for a in axes if v.shape[a % v.ndim] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v
    return apply("squeeze", _squeeze, _t(x))


def unsqueeze(x, axis, name=None):
    def _unsqueeze(v):
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        out = v
        for a in sorted(int(a) if not isinstance(a, Tensor) else int(a.item())
                        for a in axes):
            out = jnp.expand_dims(out, a)
        return out
    return apply("unsqueeze", _unsqueeze, _t(x))


def squeeze_(x, axis=None, name=None):
    return x._rebind(squeeze(x, axis))


def unsqueeze_(x, axis, name=None):
    return x._rebind(unsqueeze(x, axis))


def _concat_impl(vs, axis=0):
    return jnp.concatenate(vs, axis=axis)


def _concat_rule(vals, attrs):
    ax = attrs.get("axis", 0)
    out = jnp.concatenate(vals, axis=ax)
    a = ax if ax >= 0 else vals[0].ndim + ax
    points = np.cumsum([v.shape[a] for v in vals])[:-1].tolist()

    def vjp(ct):
        parts = jnp.split(ct, points, axis=a)
        return tuple(p.astype(v.dtype) for p, v in zip(parts, vals))
    return out, vjp


def _stack_impl(vs, axis=0):
    return jnp.stack(vs, axis=axis)


def _stack_rule(vals, attrs):
    ax = attrs.get("axis", 0)
    out = jnp.stack(vals, axis=ax)
    a = ax if ax >= 0 else out.ndim + ax

    def vjp(ct):
        return tuple(g.astype(v.dtype) for g, v in
                     zip(jnp.moveaxis(ct, a, 0), vals))
    return out, vjp


def concat(x, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply("concat", _concat_impl, list(x), axis=ax)


def stack(x, axis=0, name=None):
    return apply("stack", _stack_impl, list(x), axis=int(axis))


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)

    def _split(v):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=ax))
        sections = [int(s) for s in num_or_sections]
        total = v.shape[ax]
        known = [s for s in sections if s != -1]
        sections = [s if s != -1 else total - int(np.sum(known)) for s in sections]
        points = np.cumsum(sections)[:-1].tolist()
        return tuple(jnp.split(v, points, axis=ax))
    return list(apply("split", _split, _t(x)))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    def _unbind(v):
        return tuple(jnp.moveaxis(v, axis, 0))
    return list(apply("unbind", _unbind, _t(x)))


unstack = unbind


def tile(x, repeat_times, name=None):
    reps = _static_shape(repeat_times)
    return apply("tile", lambda v: jnp.tile(v, reps), _t(x))


def expand(x, shape, name=None):
    shape = _static_shape(shape)

    def _expand(v):
        tgt = list(shape)
        # paddle: -1 keeps original dim
        off = len(tgt) - v.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = v.shape[i - off]
        return jnp.broadcast_to(v, tuple(tgt))
    return apply("expand", _expand, _t(x))


def expand_as(x, y, name=None):
    return apply("expand_as", lambda v, w: jnp.broadcast_to(v, w.shape), _t(x), _t(y))


def broadcast_to(x, shape, name=None):
    shape = _static_shape(shape)
    return apply("broadcast_to", lambda v: jnp.broadcast_to(v, shape), _t(x))


def broadcast_tensors(inputs, name=None):
    outs = apply("broadcast_tensors",
                 lambda vs: tuple(jnp.broadcast_arrays(*vs)), list(inputs))
    return list(outs) if isinstance(outs, tuple) else [outs]


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply("flip", lambda v: jnp.flip(v, axis=tuple(axes)), _t(x))


def reverse(x, axis, name=None):
    """Alias of flip (reference: python/paddle/fluid/layers/nn.py reverse)."""
    return flip(x, axis, name=name)


def roll(x, shifts, axis=None, name=None):
    return apply("roll", lambda v: jnp.roll(v, shifts, axis=axis), _t(x))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), _t(x))


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)

    def _gather(v, idx):
        return jnp.take(v, idx.reshape(-1) if idx.ndim > 1 else idx, axis=ax)
    return apply("gather", _gather, _t(x), _t(index))


def gather_nd(x, index, name=None):
    def _gather_nd(v, idx):
        # index [..., k] indexes first k dims of v
        k = idx.shape[-1]
        out = v[tuple(jnp.moveaxis(idx, -1, 0))]
        return out
    return apply("gather_nd", _gather_nd, _t(x), _t(index))


def take(x, index, mode="raise", name=None):
    def _take(v, idx):
        return jnp.take(v.reshape(-1), idx, mode="clip" if mode != "wrap" else "wrap")
    return apply("take", _take, _t(x), _t(index))


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply("take_along_axis",
                 lambda v, idx: jnp.take_along_axis(v, idx, axis=axis),
                 _t(arr), _t(indices))


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def _put(v, idx, val):
        val = jnp.broadcast_to(val, idx.shape).astype(v.dtype)
        if reduce == "assign":
            return jnp.put_along_axis(v, idx, val, axis=axis, inplace=False)
        updater = {"add": "add", "multiply": "multiply", "mul": "multiply"}[reduce]
        # emulate via at-scatter
        ii = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        full_idx = list(ii)
        full_idx[axis] = idx
        if updater == "add":
            return v.at[tuple(full_idx)].add(val)
        return v.at[tuple(full_idx)].multiply(val)
    return apply("put_along_axis", _put, _t(arr), _t(indices), _t(values))


def scatter(x, index, updates, overwrite=True, name=None):
    def _scatter(v, idx, upd):
        if overwrite:
            return v.at[idx].set(upd)
        return v.at[idx].add(upd)
    return apply("scatter", _scatter, _t(x), _t(index), _t(updates))


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._rebind(scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    def _snd(v, idx, upd):
        return v.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply("scatter_nd_add", _snd, _t(x), _t(index), _t(updates))


def scatter_nd(index, updates, shape, name=None):
    shape = _static_shape(shape)

    def _snd(idx, upd):
        z = jnp.zeros(shape, upd.dtype)
        return z.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply("scatter_nd", _snd, _t(index), _t(updates))


def index_select(x, index, axis=0, name=None):
    return apply("index_select", lambda v, idx: jnp.take(v, idx, axis=axis),
                 _t(x), _t(index))


def index_sample(x, index, name=None):
    return apply("index_sample",
                 lambda v, idx: jnp.take_along_axis(v, idx, axis=1), _t(x), _t(index))


def index_add(x, index, axis, value, name=None):
    def _index_add(v, idx, val):
        vm = jnp.moveaxis(v, axis, 0)
        out = vm.at[idx].add(jnp.moveaxis(val, axis, 0))
        return jnp.moveaxis(out, 0, axis)
    return apply("index_add", _index_add, _t(x), _t(index), _t(value))


def index_put(x, indices, value, accumulate=False, name=None):
    def _index_put(v, idxs, val):
        key = tuple(idxs)
        if accumulate:
            return v.at[key].add(val)
        return v.at[key].set(val)
    return apply("index_put", _index_put, _t(x), [_t(i) for i in indices], _t(value))


def slice(input, axes, starts, ends, name=None):
    def _iv(a):
        return int(a.item()) if isinstance(a, Tensor) else int(a)
    axes = [_iv(a) for a in axes]
    starts = [_iv(s) for s in starts]
    ends = [_iv(e) for e in ends]

    def _slice(v):
        idx = [py_slice(None)] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            idx[a] = py_slice(s, e)
        return v[tuple(idx)]
    return apply("slice", _slice, _t(input))


def strided_slice(x, axes, starts, ends, strides, name=None):
    def _ss(v):
        idx = [py_slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[int(a)] = py_slice(int(s), int(e), int(st))
        return v[tuple(idx)]
    return apply("strided_slice", _ss, _t(x))


def crop(x, shape=None, offsets=None, name=None):
    shape = _static_shape(shape)
    offsets = [0] * len(shape) if offsets is None else [int(o) for o in offsets]

    def _crop(v):
        idx = tuple(py_slice(o, o + s) for o, s in zip(offsets, shape))
        return v[idx]
    return apply("crop", _crop, _t(x))


def repeat_interleave(x, repeats, axis=None, name=None):
    def _ri(v):
        return jnp.repeat(v, repeats, axis=axis)
    return apply("repeat_interleave", _ri, _t(x))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply("where", lambda c, a, b: jnp.where(c, a, b),
                 _t(condition), _t(x), _t(y))


def where_(condition, x, y, name=None):
    return x._rebind(where(condition, x, y))


def nonzero(x, as_tuple=False):
    if in_static_trace():
        raise RuntimeError("nonzero has data-dependent shape; not supported under jit")
    arr = np.asarray(x._value)
    res = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(r)) for r in res)
    return Tensor(jnp.asarray(np.stack(res, axis=1)))


def masked_select(x, mask, name=None):
    if in_static_trace():
        raise RuntimeError("masked_select has data-dependent shape; not supported under jit")
    arr = np.asarray(x._value)
    m = np.asarray(mask._value if isinstance(mask, Tensor) else mask)
    return Tensor(jnp.asarray(arr[np.broadcast_to(m, arr.shape)]))


def masked_fill(x, mask, value, name=None):
    return apply("masked_fill",
                 lambda v, m: jnp.where(m, jnp.asarray(value, v.dtype), v),
                 _t(x), _t(mask))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    if in_static_trace():
        raise RuntimeError("unique has data-dependent shape; not supported under jit")
    arr = np.asarray(x._value)
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    if in_static_trace():
        raise RuntimeError("unique_consecutive: data-dependent shape under jit")
    arr = np.asarray(x._value).flatten() if axis is None else np.asarray(x._value)
    keep = np.concatenate([[True], arr[1:] != arr[:-1]]) if arr.ndim == 1 else None
    if keep is None:
        raise NotImplementedError("unique_consecutive with axis")
    out = [Tensor(jnp.asarray(arr[keep]))]
    if return_inverse:
        out.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        out.append(Tensor(jnp.asarray(np.diff(np.append(idx, len(arr))))))
    return out[0] if len(out) == 1 else tuple(out)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    def _pad(v):
        p = [int(q.item()) if isinstance(q, Tensor) else int(q) for q in pad]
        nd = v.ndim
        if len(p) == 2 * nd:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # paddle convention: pair 0 = (left, right) on the LAST
            # spatial dim (W), pair 1 = (top, bottom) on H, pair 2 =
            # (front, back) on D — i.e. pairs assign from the last dim
            # INWARD (reference common.py:1187 and its circular-pad doc
            # example; forward assignment silently transposed H/W pads)
            width = [(0, 0)] * nd
            npairs = len(p) // 2
            if data_format in ("NLC", "NHWC", "NDHWC"):
                dims = list(range(nd - 2, nd - 2 - npairs, -1))
            else:
                dims = list(range(nd - 1, nd - 1 - npairs, -1))
            for i, d in enumerate(dims):
                width[d] = (p[2 * i], p[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, width, mode=jmode, constant_values=value)
        return jnp.pad(v, width, mode=jmode)
    return apply("pad", _pad, _t(x))


def as_complex(x, name=None):
    return apply("as_complex", lambda v: jax.lax.complex(v[..., 0], v[..., 1]), _t(x))


def as_real(x, name=None):
    return apply("as_real", lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1),
                 _t(x))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def atleast_1d(*inputs, name=None):
    outs = [apply("atleast_1d", jnp.atleast_1d, _t(v)) for v in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply("atleast_2d", jnp.atleast_2d, _t(v)) for v in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply("atleast_3d", jnp.atleast_3d, _t(v)) for v in inputs]
    return outs[0] if len(outs) == 1 else outs


def tensordot(x, y, axes=2, name=None):
    def _td(a, b):
        ax = axes
        if isinstance(ax, Tensor):
            ax = ax.numpy().tolist()
        if isinstance(ax, (list, tuple)):
            ax = tuple(tuple(int(i) for i in a2) if isinstance(a2, (list, tuple))
                       else int(a2) for a2 in ax)
        return jnp.tensordot(a, b, axes=ax)
    return apply("tensordot", _td, _t(x), _t(y))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def _shard(v):
        size = index_num // nshards
        lo = shard_id * size
        in_shard = (v >= lo) & (v < lo + size)
        return jnp.where(in_shard, v - lo, ignore_value)
    return apply("shard_index", _shard, _t(input), _differentiable=False)


def shape(input, name=None):
    """Shape as an int32 tensor (reference: paddle.shape op)."""
    return Tensor(jnp.asarray(_t(input)._value.shape, jnp.int32))


def rank(input, name=None):
    """Rank (ndim) as a 0-D int32 tensor."""
    return Tensor(jnp.asarray(_t(input)._value.ndim, jnp.int32))


def tolist(x):
    return _t(x).tolist()


# ------------------------------------------------- TensorArray (dygraph)
# Reference python/paddle/tensor/array.py: in dygraph these operate on a
# plain Python list (the LoDTensorArray analog).
def create_array(dtype="float32", initialized_list=None):
    array = list(initialized_list) if initialized_list is not None else []
    for v in array:
        if not isinstance(v, Tensor):
            raise TypeError(
                f"initialized_list items must be Tensors, got {type(v)}")
    return array


def array_write(x, i, array=None):
    idx = int(i.item()) if isinstance(i, Tensor) else int(i)
    if array is None:
        array = []
    while len(array) <= idx:
        array.append(None)
    array[idx] = _t(x)
    return array


def array_read(array, i):
    idx = int(i.item()) if isinstance(i, Tensor) else int(i)
    return array[idx]


def array_length(array):
    return Tensor(jnp.asarray(len(array)))  # int32 — TPU-native index width


# ------------------------------------------------- strided views
# TPU-native: XLA arrays are not strided, so these "view" ops lower to
# gathers/slices the compiler fuses (reference: paddle Tensor.unfold /
# as_strided are true views over strided memory).
def unfold(x, axis, size, step, name=None):
    """Sliding windows along `axis`: returns shape
    [..., n_windows, ..., size] with the window dim appended last
    (paddle.Tensor.unfold semantics)."""
    def _unfold(v):
        ax = axis % v.ndim
        n = (v.shape[ax] - size) // step + 1
        if n <= 0:
            raise ValueError(
                f"unfold: size {size} > dim {v.shape[ax]} along axis {ax}")
        starts = jnp.arange(n) * step
        idx = starts[:, None] + jnp.arange(size)[None, :]  # [n, size]
        out = jnp.take(v, idx.reshape(-1), axis=ax)
        out = out.reshape(v.shape[:ax] + (n, size) + v.shape[ax + 1:])
        return jnp.moveaxis(out, ax + 1, -1)
    return apply("unfold_axis", _unfold, _t(x))


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view re-expressed as a gather over the flattened buffer
    (strides are in ELEMENTS of the flat layout, matching the reference's
    as_strided over contiguous memory)."""
    shape = [int(s) for s in shape]
    stride = [int(s) for s in stride]
    if len(shape) != len(stride):
        raise ValueError("as_strided: shape and stride rank mismatch")
    if offset < 0 or any(s < 0 for s in shape) or any(
            st < 0 for st in stride):
        raise ValueError("as_strided: negative shape/stride/offset")
    size = int(np.prod(_t(x).shape)) if _t(x).shape else 1
    max_idx = offset + sum((s - 1) * st for s, st in zip(shape, stride)
                           if s > 0)
    if max_idx >= size:
        raise ValueError(
            f"as_strided: max element index {max_idx} out of bounds for "
            f"tensor of {size} elements")

    def _as_strided(v):
        flat = v.reshape(-1)
        idx = jnp.asarray(offset)
        for s, st in zip(shape, stride):
            idx = idx[..., None] + jnp.arange(s) * st
        return flat[idx.reshape(-1)].reshape(tuple(shape))
    return apply("as_strided", _as_strided, _t(x))


def vander(x, n=None, increasing=False, name=None):
    """Vandermonde matrix (reference paddle.vander semantics; numpy's
    column order is decreasing by default, same here)."""
    def _vander(v):
        if v.ndim != 1:
            raise ValueError("vander expects a 1-D tensor")
        cols = v.shape[0] if n is None else int(n)
        powers = jnp.arange(cols)
        if not increasing:
            powers = powers[::-1]
        return v[:, None] ** powers[None, :].astype(v.dtype)
    return apply("vander", _vander, _t(x))


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal-rule integral along `axis` (paddle.trapezoid)."""
    if x is not None and dx is not None:
        raise ValueError("trapezoid: pass either x or dx, not both")

    if x is None:
        d = 1.0 if dx is None else dx

        def _trap(yv):
            ys = jnp.moveaxis(yv, axis, -1)
            return jnp.sum((ys[..., 1:] + ys[..., :-1]) * (d / 2.0), -1)
        return apply("trapezoid", _trap, _t(y))

    def _trap2(yv, xv):
        ys = jnp.moveaxis(yv, axis, -1)
        if xv.ndim == 1:
            dxs = xv[1:] - xv[:-1]
        else:
            xs = jnp.moveaxis(xv, axis, -1)
            dxs = xs[..., 1:] - xs[..., :-1]
        return jnp.sum((ys[..., 1:] + ys[..., :-1]) * dxs / 2.0, -1)
    return apply("trapezoid", _trap2, _t(y), _t(x))


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None and dx is not None:
        raise ValueError("cumulative_trapezoid: pass either x or dx")
    if x is None:
        d = 1.0 if dx is None else dx

        def _ct(yv):
            ys = jnp.moveaxis(yv, axis, -1)
            seg = (ys[..., 1:] + ys[..., :-1]) * (d / 2.0)
            return jnp.moveaxis(jnp.cumsum(seg, -1), -1, axis)
        return apply("cumulative_trapezoid", _ct, _t(y))

    def _ct2(yv, xv):
        ys = jnp.moveaxis(yv, axis, -1)
        if xv.ndim == 1:
            dxs = xv[1:] - xv[:-1]
        else:
            dxs = jnp.moveaxis(xv, axis, -1)
            dxs = dxs[..., 1:] - dxs[..., :-1]
        seg = (ys[..., 1:] + ys[..., :-1]) * dxs / 2.0
        return jnp.moveaxis(jnp.cumsum(seg, -1), -1, axis)
    return apply("cumulative_trapezoid", _ct2, _t(y), _t(x))


def _register_manipulation_rules():
    from ..core.dispatch import register_eager_vjp

    register_eager_vjp("reshape", _reshape_impl, _reshape_rule)
    register_eager_vjp("transpose", _transpose_impl, _transpose_rule)
    register_eager_vjp("concat", _concat_impl, _concat_rule,
                       allow_containers=True)
    register_eager_vjp("stack", _stack_impl, _stack_rule,
                       allow_containers=True)


_register_manipulation_rules()
