"""Elementwise + reduction math ops (reference: python/paddle/tensor/math.py).

Every op is a functional jnp computation dispatched through the tape
(core/dispatch.py); XLA fuses elementwise chains automatically, which is what
the reference's fusion passes (/root/reference/paddle/fluid/framework/ir) do
by hand.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.dtype import to_np
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _binop(op_name, fn):
    # NB: the public `name=None` kwarg (paddle API) must not shadow the
    # op's dispatch name — it silently became None for every binop once
    def op(x, y, name=None):
        return apply(op_name, fn, _t(x), _t(y))
    op.__name__ = op_name
    return op


def _unop(op_name, fn):
    def op(x, name=None):
        return apply(op_name, fn, _t(x))
    op.__name__ = op_name
    return op


add = _binop("add", jnp.add)
subtract = _binop("subtract", jnp.subtract)
multiply = _binop("multiply", jnp.multiply)
divide = _binop("divide", jnp.divide)
floor_divide = _binop("floor_divide", jnp.floor_divide)
mod = _binop("mod", jnp.mod)
remainder = mod
floor_mod = mod
maximum = _binop("maximum", jnp.maximum)
minimum = _binop("minimum", jnp.minimum)
fmax = _binop("fmax", jnp.fmax)
fmin = _binop("fmin", jnp.fmin)
atan2 = _binop("atan2", jnp.arctan2)
gcd = _binop("gcd", jnp.gcd)
lcm = _binop("lcm", jnp.lcm)
heaviside = _binop("heaviside", jnp.heaviside)
copysign = _binop("copysign", jnp.copysign)
nextafter = _binop("nextafter", jnp.nextafter)
ldexp = _binop("ldexp", jnp.ldexp)
hypot = _binop("hypot", jnp.hypot)
logaddexp = _binop("logaddexp", jnp.logaddexp)


def pow(x, y, name=None):
    return apply("pow", jnp.power, _t(x), _t(y))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def _scale(v, s, b):
        out = v * s + b if bias_after_scale else (v + b) * s
        return out
    out = apply("scale", _scale, _t(x), _t(scale), _t(bias))
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


sqrt = _unop("sqrt", jnp.sqrt)
rsqrt = _unop("rsqrt", jax.lax.rsqrt)
exp = _unop("exp", jnp.exp)
expm1 = _unop("expm1", jnp.expm1)
log = _unop("log", jnp.log)
log2 = _unop("log2", jnp.log2)
log10 = _unop("log10", jnp.log10)
log1p = _unop("log1p", jnp.log1p)
abs = _unop("abs", jnp.abs)
neg = _unop("neg", jnp.negative)
sign = _unop("sign", jnp.sign)
sin = _unop("sin", jnp.sin)
cos = _unop("cos", jnp.cos)
tan = _unop("tan", jnp.tan)
asin = _unop("asin", jnp.arcsin)
acos = _unop("acos", jnp.arccos)
atan = _unop("atan", jnp.arctan)
sinh = _unop("sinh", jnp.sinh)
cosh = _unop("cosh", jnp.cosh)
tanh = _unop("tanh", jnp.tanh)
asinh = _unop("asinh", jnp.arcsinh)
acosh = _unop("acosh", jnp.arccosh)
atanh = _unop("atanh", jnp.arctanh)
floor = _unop("floor", jnp.floor)
ceil = _unop("ceil", jnp.ceil)
round = _unop("round", jnp.round)
trunc = _unop("trunc", jnp.trunc)
frac = _unop("frac", lambda v: v - jnp.trunc(v))
reciprocal = _unop("reciprocal", jnp.reciprocal)
square = _unop("square", jnp.square)
erf = _unop("erf", jax.scipy.special.erf)
erfinv = _unop("erfinv", jax.scipy.special.erfinv)
lgamma = _unop("lgamma", jax.scipy.special.gammaln)
digamma = _unop("digamma", jax.scipy.special.digamma)
i0 = _unop("i0", jax.scipy.special.i0)
i0e = _unop("i0e", jax.scipy.special.i0e)
i1 = _unop("i1", jax.scipy.special.i1)
i1e = _unop("i1e", jax.scipy.special.i1e)
angle = _unop("angle", jnp.angle)
conj = _unop("conj", jnp.conj)
real = _unop("real", jnp.real)
imag = _unop("imag", jnp.imag)
deg2rad = _unop("deg2rad", jnp.deg2rad)
rad2deg = _unop("rad2deg", jnp.rad2deg)
sigmoid = _unop("sigmoid", jax.nn.sigmoid)
logit = _unop("logit", jax.scipy.special.logit)
isnan = _unop("isnan", jnp.isnan)
isinf = _unop("isinf", jnp.isinf)
isfinite = _unop("isfinite", jnp.isfinite)
isneginf = _unop("isneginf", jnp.isneginf)
isposinf = _unop("isposinf", jnp.isposinf)


def clip(x, min=None, max=None, name=None):
    def _v(a):
        return a._value if isinstance(a, Tensor) else a
    return apply("clip", lambda v: jnp.clip(v, _v(min), _v(max)), _t(x))


def lerp(x, y, weight, name=None):
    return apply("lerp", lambda a, b, w: a + w * (b - a), _t(x), _t(y), _t(weight))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply("nan_to_num",
                 lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf),
                 _t(x))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), _t(x))


def multiplex(inputs, index, name=None):
    def _mux(ins, idx):
        stacked = jnp.stack(ins, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0
        )[0]
    return apply("multiplex", _mux, list(inputs), _t(index))


# ------------------------------------------------------------------ reductions
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.numpy().tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---- reduction impls: module-level with static attrs so the analytic
# eager-VJP rules below can match them by identity (VERDICT r3 #2: the
# jax.vjp fallback re-linearizes per call — pure overhead in eager loops)
def _sum_impl(v, axis=None, dtype=None, keepdims=False):
    return jnp.sum(v, axis=axis, dtype=dtype, keepdims=keepdims)


def _mean_impl(v, axis=None, keepdims=False):
    return jnp.mean(v, axis=axis, keepdims=keepdims)


def _max_impl(v, axis=None, keepdims=False):
    return jnp.max(v, axis=axis, keepdims=keepdims)


def _min_impl(v, axis=None, keepdims=False):
    return jnp.min(v, axis=axis, keepdims=keepdims)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply("sum", _sum_impl, _t(x), axis=_axis(axis),
                 dtype=to_np(dtype), keepdims=keepdim)


def mean(x, axis=None, keepdim=False, name=None):
    return apply("mean", _mean_impl, _t(x), axis=_axis(axis),
                 keepdims=keepdim)


def max(x, axis=None, keepdim=False, name=None):
    return apply("max", _max_impl, _t(x), axis=_axis(axis), keepdims=keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return apply("min", _min_impl, _t(x), axis=_axis(axis), keepdims=keepdim)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return apply("prod",
                 lambda v: jnp.prod(v, axis=_axis(axis), dtype=to_np(dtype),
                                    keepdims=keepdim), _t(x))


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply("nansum",
                 lambda v: jnp.nansum(v, axis=_axis(axis), dtype=to_np(dtype),
                                      keepdims=keepdim), _t(x))


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply("nanmean",
                 lambda v: jnp.nanmean(v, axis=_axis(axis), keepdims=keepdim), _t(x))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply("count_nonzero",
                 lambda v: jnp.count_nonzero(v, axis=_axis(axis), keepdims=keepdim),
                 _t(x), _differentiable=False)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply("logsumexp",
                 lambda v: jax.scipy.special.logsumexp(v, axis=_axis(axis),
                                                       keepdims=keepdim), _t(x))


def cumsum(x, axis=None, dtype=None, name=None):
    def _cumsum(v):
        if axis is None:
            v = v.reshape(-1)
            return jnp.cumsum(v, dtype=to_np(dtype))
        return jnp.cumsum(v, axis=_axis(axis), dtype=to_np(dtype))
    return apply("cumsum", _cumsum, _t(x))


def cumprod(x, dim=None, dtype=None, name=None):
    def _cumprod(v):
        if dim is None:
            v = v.reshape(-1)
            return jnp.cumprod(v, dtype=to_np(dtype))
        return jnp.cumprod(v, axis=int(dim), dtype=to_np(dtype))
    return apply("cumprod", _cumprod, _t(x))


def cummax(x, axis=None, dtype="int64", name=None):
    def _cummax(v):
        if axis is None:
            v = v.reshape(-1)
            ax = 0
        else:
            ax = int(axis)
        vals = jax.lax.cummax(v, axis=ax)
        return vals
    return apply("cummax", _cummax, _t(x))


def cummin(x, axis=None, dtype="int64", name=None):
    def _cummin(v):
        ax = 0 if axis is None else int(axis)
        v2 = v.reshape(-1) if axis is None else v
        return jax.lax.cummin(v2, axis=ax)
    return apply("cummin", _cummin, _t(x))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    def _v(a):
        return a._value if isinstance(a, Tensor) else a
    return apply("diff",
                 lambda v: jnp.diff(v, n=n, axis=axis, prepend=_v(prepend),
                                    append=_v(append)), _t(x))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace",
                 lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2),
                 _t(x))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("diagonal",
                 lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2),
                 _t(x))


# ------------------------------------------------------------------- matmul &c
def _matmul_impl(a, b, transpose_x=False, transpose_y=False):
    if transpose_x:
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if transpose_y:
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    return jnp.matmul(a, b)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return apply("matmul", _matmul_impl, _t(x), _t(y),
                 transpose_x=transpose_x, transpose_y=transpose_y)


mm = matmul


def bmm(x, y, name=None):
    return apply("bmm", jnp.matmul, _t(x), _t(y))


def dot(x, y, name=None):
    def _dot(a, b):
        return jnp.sum(a * b, axis=-1)
    return apply("dot", _dot, _t(x), _t(y))


def inner(x, y, name=None):
    return apply("inner", jnp.inner, _t(x), _t(y))


def outer(x, y, name=None):
    return apply("outer", lambda a, b: jnp.outer(a, b), _t(x), _t(y))


def kron(x, y, name=None):
    return apply("kron", jnp.kron, _t(x), _t(y))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply("addmm", lambda i, a, b: beta * i + alpha * (a @ b),
                 _t(input), _t(x), _t(y))


def inverse(x, name=None):
    return apply("inverse", jnp.linalg.inv, _t(x))


# ------------------------------------------------------------------ misc
def increment(x, value=1.0, name=None):
    out = apply("increment", lambda v: v + value, _t(x))
    x._rebind(out)
    return x


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    def _acc(logits, lab):
        topk_idx = jax.lax.top_k(logits, k)[1]
        lab2 = lab.reshape(-1, 1)
        hit = jnp.any(topk_idx == lab2, axis=1)
        return jnp.mean(hit.astype(jnp.float32))
    return apply("accuracy", _acc, _t(input), _t(label), _differentiable=False)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def add_n(inputs, name=None):
    """Elementwise sum of a list of tensors (reference: paddle.add_n over
    the sum op, python/paddle/tensor/math.py)."""
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    def _sum(*vals):
        out = vals[0]
        for v in vals[1:]:
            out = out + v
        return out
    return apply("add_n", _sum, *[_t(v) for v in inputs])


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize sub-tensors along `axis` whose p-norm exceeds max_norm
    (reference: python/paddle/tensor/math.py renorm)."""
    def _renorm(v):
        nd = v.ndim
        ax = axis if axis >= 0 else axis + nd
        reduce_axes = tuple(i for i in range(nd) if i != ax)
        norms = jnp.sum(jnp.abs(v) ** p, axis=reduce_axes,
                        keepdims=True) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * scale
    return apply("renorm", _renorm, _t(x))


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """Cumulative logsumexp (reference: python/paddle/tensor/math.py
    logcumsumexp).  dtype casts the INPUT before computing, like the
    reference."""
    def _lce(v):
        if dtype is not None:
            v = v.astype(to_np(dtype))
        ax = axis
        if ax is None:
            v = v.reshape(-1)
            ax = 0
        vmax = jnp.max(v, axis=ax, keepdims=True)
        out = jnp.log(jnp.cumsum(jnp.exp(v - vmax), axis=ax)) + vmax
        return out
    return apply("logcumsumexp", _lce, _t(x))


# --------------------------------------------------------------------------
# Analytic eager-VJP rules for the reduction / matmul hot set
# (core/dispatch.py register_eager_vjp; reference analog: the codegen'd
# GradNode pairs the tracer records instead of re-linearizing,
# imperative/tracer.cc TraceOpImpl).
def _reduce_axes(shape, axis):
    if axis is None:
        return tuple(range(len(shape)))
    axes = axis if isinstance(axis, tuple) else (axis,)
    return tuple(ax % len(shape) for ax in axes)


def _expand_like(ct, shape, axes, keepdims):
    if not keepdims:
        for ax in sorted(axes):
            ct = jnp.expand_dims(ct, ax)
    return ct


def _sum_rule(vals, attrs):
    if attrs.get("dtype") is not None:
        return None
    (a,) = vals
    axis, keepdims = attrs.get("axis"), attrs.get("keepdims", False)
    out = jnp.sum(a, axis=axis, keepdims=keepdims)
    axes = _reduce_axes(a.shape, axis)

    def vjp(ct):
        g = _expand_like(ct, a.shape, axes, keepdims)
        return (jnp.broadcast_to(g, a.shape).astype(a.dtype),)
    return out, vjp


def _mean_rule(vals, attrs):
    (a,) = vals
    axis, keepdims = attrs.get("axis"), attrs.get("keepdims", False)
    out = jnp.mean(a, axis=axis, keepdims=keepdims)
    axes = _reduce_axes(a.shape, axis)
    n = 1
    for ax in axes:
        n *= a.shape[ax]

    def vjp(ct):
        g = _expand_like(ct, a.shape, axes, keepdims) / n
        return (jnp.broadcast_to(g, a.shape).astype(a.dtype),)
    return out, vjp


def _minmax_rule(reducer):
    def rule(vals, attrs):
        (a,) = vals
        axis, keepdims = attrs.get("axis"), attrs.get("keepdims", False)
        out = reducer(a, axis=axis, keepdims=keepdims)
        axes = _reduce_axes(a.shape, axis)

        def vjp(ct):
            # jax convention: split the cotangent evenly among ties
            full = _expand_like(out, a.shape, axes, keepdims)
            mask = (a == full).astype(a.dtype)
            ties = jnp.sum(mask, axis=axes, keepdims=True)
            g = _expand_like(ct, a.shape, axes, keepdims)
            return ((g * mask / ties).astype(a.dtype),)
        return out, vjp
    return rule


def _matmul_rule(vals, attrs):
    a, b = vals
    if a.ndim < 2 or b.ndim < 2:
        return None  # vector cases: rare, let jax.vjp handle the contraction
    tx = attrs.get("transpose_x", False)
    ty = attrs.get("transpose_y", False)
    A = jnp.swapaxes(a, -1, -2) if tx else a
    B = jnp.swapaxes(b, -1, -2) if ty else b
    out = jnp.matmul(A, B)

    def vjp(ct):
        gA = jnp.matmul(ct, jnp.swapaxes(B, -1, -2))
        gB = jnp.matmul(jnp.swapaxes(A, -1, -2), ct)
        ga = jnp.swapaxes(gA, -1, -2) if tx else gA
        gb = jnp.swapaxes(gB, -1, -2) if ty else gB
        from ..core.dispatch import _unbroadcast
        return (_unbroadcast(ga, a.shape, a.dtype),
                _unbroadcast(gb, b.shape, b.dtype))
    return out, vjp


def _register_math_rules():
    from ..core.dispatch import register_eager_vjp

    register_eager_vjp("sum", _sum_impl, _sum_rule)
    register_eager_vjp("mean", _mean_impl, _mean_rule)
    register_eager_vjp("max", _max_impl, _minmax_rule(jnp.max))
    register_eager_vjp("min", _min_impl, _minmax_rule(jnp.min))
    register_eager_vjp("matmul", _matmul_impl, _matmul_rule)
    register_eager_vjp("bmm", jnp.matmul, _matmul_rule)


_register_math_rules()
