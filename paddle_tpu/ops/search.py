"""Search/sort ops (reference: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.dtype import to_np
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def _argmax(v):
        out = jnp.argmax(v if axis is not None else v.reshape(-1),
                         axis=axis if axis is not None else 0)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(to_np(dtype))
    return apply("argmax", _argmax, _t(x), _differentiable=False)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def _argmin(v):
        out = jnp.argmin(v if axis is not None else v.reshape(-1),
                         axis=axis if axis is not None else 0)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(to_np(dtype))
    return apply("argmin", _argmin, _t(x), _differentiable=False)


def argsort(x, axis=-1, descending=False, stable=True, name=None):
    def _argsort(v):
        idx = jnp.argsort(v, axis=axis, stable=stable, descending=descending)
        return idx.astype(jnp.int64)
    return apply("argsort", _argsort, _t(x), _differentiable=False)


def sort(x, axis=-1, descending=False, stable=True, name=None):
    def _sort(v):
        out = jnp.sort(v, axis=axis, stable=stable, descending=descending)
        return out
    return apply("sort", _sort, _t(x))


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)

    def _topk(v):
        ax = axis if axis is not None else v.ndim - 1
        vm = jnp.moveaxis(v, ax, -1)
        src = vm if largest else -vm
        vals, idx = jax.lax.top_k(src, kk)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax).astype(jnp.int64))
    return apply("topk", _topk, _t(x))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def _kth(v):
        ax = axis % v.ndim
        vals = jnp.sort(v, axis=ax)
        idxs = jnp.argsort(v, axis=ax)
        take = jnp.take(vals, k - 1, axis=ax)
        take_i = jnp.take(idxs, k - 1, axis=ax).astype(jnp.int64)
        if keepdim:
            take = jnp.expand_dims(take, ax)
            take_i = jnp.expand_dims(take_i, ax)
        return take, take_i
    return apply("kthvalue", _kth, _t(x))


def mode(x, axis=-1, keepdim=False, name=None):
    def _mode(v):
        ax = axis % v.ndim
        vm = jnp.moveaxis(v, ax, -1)
        sorted_v = jnp.sort(vm, axis=-1)
        n = sorted_v.shape[-1]
        runs = jnp.concatenate(
            [jnp.ones(sorted_v.shape[:-1] + (1,), bool),
             sorted_v[..., 1:] != sorted_v[..., :-1]], axis=-1)
        run_id = jnp.cumsum(runs, axis=-1) - 1
        counts = jax.nn.one_hot(run_id, n, dtype=jnp.int32).sum(axis=-2)
        best_run = jnp.argmax(counts, axis=-1)
        first_idx_of_run = jnp.argmax(run_id == best_run[..., None], axis=-1)
        values = jnp.take_along_axis(sorted_v, first_idx_of_run[..., None], -1)[..., 0]
        # reference funcs/mode.h:113 records the index at the END of the
        # sorted run — the LAST original occurrence of the mode value
        # (torch agrees); argmax-over-equality would give the first
        rev_pos = jnp.argmax((vm == values[..., None])[..., ::-1], axis=-1)
        orig_idx = (n - 1 - rev_pos).astype(jnp.int64)
        if keepdim:
            return (jnp.expand_dims(jnp.moveaxis(values, -1, -1), ax),
                    jnp.expand_dims(orig_idx, ax))
        return values, orig_idx
    return apply("mode", _mode, _t(x))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def _ss(seq, vals):
        side = "right" if right else "left"
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, vals, side=side)
        else:
            out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
                seq.reshape(-1, seq.shape[-1]), vals.reshape(-1, vals.shape[-1]))
            out = out.reshape(vals.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply("searchsorted", _ss, _t(sorted_sequence), _t(values),
                 _differentiable=False)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def index_fill(x, index, axis, value, name=None):
    def _fill(v, idx):
        vm = jnp.moveaxis(v, axis, 0)
        vm = vm.at[idx].set(jnp.asarray(value, v.dtype))
        return jnp.moveaxis(vm, 0, axis)
    return apply("index_fill", _fill, _t(x), _t(index))
