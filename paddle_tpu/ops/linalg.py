"""Linear algebra ops (reference: python/paddle/tensor/linalg.py, paddle.linalg).

All decompositions lower to XLA's linalg custom calls via jax.numpy.linalg.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def _norm(v):
        ord_ = p
        if ord_ == "fro" or ord_ is None:
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(v)))
            ord_ = None if isinstance(axis, (list, tuple)) else 2
        if ord_ == "inf":
            ord_ = jnp.inf
        elif ord_ == "-inf":
            ord_ = -jnp.inf
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if ax is None and ord_ is not None:
            # vector norm over flattened input
            return jnp.linalg.norm(v.reshape(-1), ord=ord_, keepdims=False)
        return jnp.linalg.norm(v, ord=ord_, axis=ax, keepdims=keepdim)
    return apply("norm", _norm, _t(x))


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply(
        "matrix_norm",
        lambda v: jnp.linalg.norm(
            v, ord=p if p != "inf" else jnp.inf, axis=tuple(axis), keepdims=keepdim
        ),
        _t(x),
    )


def dist(x, y, p=2, name=None):
    return apply("dist", lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p),
                 _t(x), _t(y))


def det(x, name=None):
    return apply("det", jnp.linalg.det, _t(x))


def slogdet(x, name=None):
    def _slogdet(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])
    return apply("slogdet", _slogdet, _t(x))


def inv(x, name=None):
    return apply("inv", jnp.linalg.inv, _t(x))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv", lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian),
                 _t(x))


def svd(x, full_matrices=False, name=None):
    return apply("svd", lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)),
                 _t(x))


def svdvals(x, name=None):
    return apply("svdvals", lambda v: jnp.linalg.svd(v, compute_uv=False), _t(x))


def qr(x, mode="reduced", name=None):
    out = apply("qr", lambda v: tuple(jnp.linalg.qr(v, mode=mode)), _t(x))
    return out


def eig(x, name=None):
    return apply("eig", lambda v: tuple(jnp.linalg.eig(v)), _t(x),
                 _differentiable=False)


def eigh(x, UPLO="L", name=None):
    return apply("eigh", lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), _t(x))


def eigvals(x, name=None):
    return apply("eigvals", jnp.linalg.eigvals, _t(x), _differentiable=False)


def eigvalsh(x, UPLO="L", name=None):
    return apply("eigvalsh", lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), _t(x))


def cholesky(x, upper=False, name=None):
    def _chol(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return apply("cholesky", _chol, _t(x))


def cholesky_solve(x, y, upper=False, name=None):
    def _cs(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply("cholesky_solve", _cs, _t(x), _t(y))


def solve(x, y, name=None):
    return apply("solve", jnp.linalg.solve, _t(x), _t(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def _ts(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply("triangular_solve", _ts, _t(x), _t(y))


def lstsq(x, y, rcond=None, driver=None, name=None):
    def _lstsq(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply("lstsq", _lstsq, _t(x), _t(y), _differentiable=False)


def matrix_power(x, n, name=None):
    return apply("matrix_power", lambda v: jnp.linalg.matrix_power(v, n), _t(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply("matrix_rank",
                 lambda v: jnp.linalg.matrix_rank(v, rtol=tol),
                 _t(x), _differentiable=False)


def cross(x, y, axis=9, name=None):
    def _cross(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply("cross", _cross, _t(x), _t(y))


def multi_dot(x, name=None):
    return apply("multi_dot", lambda vs: jnp.linalg.multi_dot(vs), list(x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    def _cov(v):
        return jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0)
    return apply("cov", _cov, _t(x))


def corrcoef(x, rowvar=True, name=None):
    return apply("corrcoef", lambda v: jnp.corrcoef(v, rowvar=rowvar), _t(x))


def cond(x, p=None, name=None):
    return apply("cond", lambda v: jnp.linalg.cond(v, p=p), _t(x),
                 _differentiable=False)


def lu(x, pivot=True, get_infos=False, name=None):
    def _lu(v):
        lu_mat, piv = jax.scipy.linalg.lu_factor(v)
        # LAPACK/paddle convention: 1-indexed pivots
        return lu_mat, piv.astype(jnp.int32) + 1
    out = apply("lu", _lu, _t(x), _differentiable=False)
    if get_infos:
        return out[0], out[1], Tensor(jnp.zeros((), jnp.int32))
    return out


def householder_product(x, tau, name=None):
    def _hp(v, t):
        m, n = v.shape[-2], v.shape[-1]
        eye = jnp.eye(m, dtype=v.dtype)
        q = jnp.broadcast_to(eye, v.shape[:-2] + (m, m)).copy() if v.ndim > 2 else eye
        for i in range(t.shape[-1]):
            w = v[..., :, i]
            w = jnp.where(jnp.arange(m) < i, 0.0, w)
            w = w.at[..., i].set(1.0) if w.ndim == 1 else w
            h = jnp.eye(m, dtype=v.dtype) - t[..., i] * jnp.outer(w, w)
            q = q @ h
        return q[..., :, :n]
    return apply("householder_product", _hp, _t(x), _t(tau))


def mv(x, vec, name=None):
    """Matrix-vector product (reference: python/paddle/tensor/linalg.py mv)."""
    return apply("mv", lambda m, v: m @ v, _t(x), _t(vec))


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack paddle.linalg.lu results into (P, L, U) (reference:
    python/paddle/tensor/linalg.py lu_unpack).  Batched LU data is
    supported; disabled parts return None like the reference."""
    L = U = P = None
    if unpack_ludata:
        def _unpack(lu_mat):
            m, n = lu_mat.shape[-2], lu_mat.shape[-1]
            k = min(m, n)
            L_ = jnp.tril(lu_mat[..., :, :k], -1) + jnp.eye(
                m, k, dtype=lu_mat.dtype)
            U_ = jnp.triu(lu_mat[..., :k, :])
            return L_, U_
        L, U = apply("lu_unpack", _unpack, _t(x))
    if unpack_pivots:
        # pivots (1-indexed LAPACK row swaps) -> permutation matrices,
        # per batch element (host math, int path)
        import numpy as np

        piv = np.asarray(_t(y)._value)
        m = int(_t(x)._value.shape[-2])
        batch_shape = piv.shape[:-1]
        piv2 = piv.reshape(-1, piv.shape[-1])
        Ps = np.zeros((piv2.shape[0], m, m), np.float32)
        for b in range(piv2.shape[0]):
            perm = np.arange(m)
            for i in range(min(m, piv2.shape[1])):
                j = int(piv2[b, i]) - 1
                perm[i], perm[j] = perm[j], perm[i]
            Ps[b, perm, np.arange(m)] = 1.0
        P = Tensor(jnp.asarray(Ps.reshape(batch_shape + (m, m))))
    return P, L, U
