# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Llama model family (Llama-2/3 architecture) — the flagship pretrain config
(BASELINE.md config 3).

The 2022 reference snapshot predates Llama; its closest analogs are the
fused transformer ops (/root/reference/paddle/fluid/operators/fused/
fused_multi_transformer_op.cu) and the Fleet mp_layers the model composes
with.  TPU-native design:
  - weights bf16, attention via the Pallas flash kernel (paddle_tpu/kernels)
  - RMSNorm via the fused Pallas kernel
  - tensor parallel through GSPMD-annotated Column/RowParallel layers
  - sequence axis shardable ("sp") for context parallelism
  - rotary embeddings precomputed once per max_position
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..distributed.parallel_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)
from ..nn import functional as F


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    sequence_parallel: bool = False
    # Chunked fused lm-head + cross-entropy: the [B,T,V] logits are never
    # materialized in HBM (computed per token-chunk under remat).  Saves
    # ~4x vocab*tokens bytes of activation memory on the pretrain path;
    # forward(labels=...) then returns (loss, None).  Opt-in (off by
    # default) because callers that consume logits — token accuracy,
    # per-token ppl, distillation — would silently get None.
    fused_lm_loss: bool = False
    lm_loss_chunk: int = 2048
    # Per-decoder-layer activation rematerialization (reference:
    # fleet/utils/recompute.py) — XLA recomputes the layer in backward,
    # cutting live activations to ~one layer's worth.
    recompute: bool = False
    # Mixture-of-experts MLP (GShard-style top-k routing through
    # kernels/moe_dispatch; reference analog: incubate moe_layer over
    # global_scatter/global_gather).  0 experts = dense LlamaMLP.
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    # Sequence/context parallelism for the no-cache attention path:
    # "" (dense), "ring" (kernels/ring_attention) or "ulysses".  Falls
    # back to dense attention when the active mesh has no `sp` axis.
    context_parallel: str = ""
    dtype: str = "bfloat16"

    @staticmethod
    def llama3_8b(**overrides):
        cfg = LlamaConfig(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=8192,
            rope_theta=500000.0)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg

    @staticmethod
    def tiny(**overrides):
        cfg = LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, dtype="float32")
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


def precompute_rope(head_dim, max_pos, theta):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                                / head_dim))
    t = jnp.arange(max_pos, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [T, D/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x, cos, sin, position_offset=0):
    """x: [B, T, H, D].  Rotate-half convention.  position_offset may be
    a traced scalar (static-cache decode compiles ONE step program) or a
    traced [B] vector of per-sequence positions (continuous-batching
    decode: every sequence in the bucket sits at its own frontier)."""
    T = x.shape[1]
    if jnp.ndim(position_offset):
        pos = jnp.asarray(position_offset)[:, None] + jnp.arange(T)
        c = cos[pos][:, :, None, :]     # [B, T, 1, D/2]
        s = sin[pos][:, :, None, :]
        x1, x2 = jnp.split(x, 2, axis=-1)
        out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
        return out.astype(x.dtype)
    c = jax.lax.dynamic_slice_in_dim(cos, position_offset, T)[
        None, :, None, :]
    s = jax.lax.dynamic_slice_in_dim(sin, position_offset, T)[
        None, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


class StaticKVCache:
    """Preallocated decode cache (TPU-native: a concat-growing cache
    changes shape every token, forcing an XLA recompile per step; a
    fixed-size buffer + dynamic_update_slice keeps ONE compiled decode
    program for the whole generation).  The reference's analog is the
    ring buffer inside fused_multi_transformer_op.cu's CacheKV."""

    __slots__ = ("k", "v")

    def __init__(self, k, v):
        self.k = k  # [B, max_len, kv_heads, head_dim]
        self.v = v

    @staticmethod
    def empty(batch, max_len, kv_heads, head_dim, dtype):
        z = jnp.zeros((batch, max_len, kv_heads, head_dim), dtype)
        return StaticKVCache(z, z)

    def tree_flatten(self):
        return (self.k, self.v), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    StaticKVCache, lambda c: c.tree_flatten(),
    StaticKVCache.tree_unflatten)


class PagedKVCache:
    """Block-pool cache view for continuous-batching decode (the serving
    engine's substrate; PAPERS.md: vLLM's PagedAttention over Orca's
    iteration-level scheduling).  ``k``/``v`` are SHARED physical pools of
    shape [num_blocks, block_size, kv_heads, head_dim]; ``block_table``
    [B, max_blocks] maps each sequence's logical block i to a pool block
    id.  Per-sequence write frontiers ride in as the (vector)
    ``position_offset`` of the forward call, exactly as the scalar offset
    does for :class:`StaticKVCache` — every shape is fixed, so ONE
    compiled decode step serves every mix of sequences forever.

    Unallocated/retired table entries may point anywhere (the engine uses
    a reserved garbage block): attention masks keys past each sequence's
    frontier, so stale pool contents are never observable.

    Quantized pools (``kv_dtype`` of ``"int8"``/``"fp8"``) carry int8
    CODE pools plus per-(block, token)-row f32 absmax scales
    (``k_scale``/``v_scale`` [num_blocks, block_size]); writes quantize
    in-trace and reads dequantize at the kernel DMA boundary
    (kernels/kv_quant.py).  ``kv_dtype`` is pytree aux data, so fp32
    and quantized caches trace as DIFFERENT treedefs and can never
    silently share a compiled step.
    """

    __slots__ = ("k", "v", "block_table", "k_scale", "v_scale",
                 "kv_dtype")

    def __init__(self, k, v, block_table, k_scale=None, v_scale=None,
                 kv_dtype=None):
        self.k = k              # [num_blocks, block_size, kv_heads, head_dim]
        self.v = v
        self.block_table = block_table      # [B, max_blocks] int32
        self.k_scale = k_scale  # [num_blocks, block_size] f32 or None
        self.v_scale = v_scale
        self.kv_dtype = kv_dtype            # None / "int8" / "fp8"

    @property
    def block_size(self):
        return self.k.shape[1]

    def tree_flatten(self):
        return (self.k, self.v, self.block_table, self.k_scale,
                self.v_scale), self.kv_dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, kv_dtype=aux)


jax.tree_util.register_pytree_node(
    PagedKVCache, lambda c: c.tree_flatten(),
    PagedKVCache.tree_unflatten)


class LlamaRMSNorm(nn.Layer):
    def __init__(self, hidden_size, eps=1e-5):
        super().__init__()
        from ..nn import initializer as I

        self._epsilon = eps
        self.weight = self.create_parameter(
            [hidden_size], default_initializer=I.Constant(1.0))

    def forward(self, x):
        from ..core.flags import flag

        def _rms(v, w):
            if flag("use_pallas_kernels") and jax.default_backend() == "tpu":
                from ..kernels.rms_norm import rms_norm as pallas_rms

                return pallas_rms(v, w, self._epsilon)
            var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1,
                           keepdims=True)
            return (v.astype(jnp.float32) * jax.lax.rsqrt(
                var + self._epsilon)).astype(v.dtype) * w
        return apply("rms_norm", _rms, x, self.weight)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        h = config.hidden_size
        self.q_proj = ColumnParallelLinear(
            h, self.num_heads * self.head_dim, has_bias=False,
            gather_output=False)
        self.k_proj = ColumnParallelLinear(
            h, self.num_kv_heads * self.head_dim, has_bias=False,
            gather_output=False)
        self.v_proj = ColumnParallelLinear(
            h, self.num_kv_heads * self.head_dim, has_bias=False,
            gather_output=False)
        self.o_proj = RowParallelLinear(
            self.num_heads * self.head_dim, h, has_bias=False,
            input_is_parallel=True)

    def forward(self, hidden, cos, sin, attn_mask=None, cache=None,
                position_offset=0, norm_weight=None, norm_eps=None):
        B, T = hidden.shape[0], hidden.shape[1]
        # head count derived from the projection's ACTUAL width: under
        # manual TP (shard_map pipeline stages) q/k/v are mp-local shards
        # holding num_heads/mp heads; under GSPMD they are global
        if norm_weight is not None:
            # fused serving epilogue: the decoder layer skipped its
            # input_layernorm and handed us the UNNORMALIZED hidden —
            # the norm folds into each projection's matmul prologue, so
            # the normalized activation never round-trips HBM.  The row
            # scale is computed once and shared by q/k/v.
            def _fused_qkv(hv, nw, wq, wk, wv):
                from ..kernels.fused_norm_linear import (fused_norm_linear,
                                                         rms_scale)

                rs = rms_scale(hv, norm_eps)
                return (fused_norm_linear(hv, rs, nw, wq),
                        fused_norm_linear(hv, rs, nw, wk),
                        fused_norm_linear(hv, rs, nw, wv))

            q, k, v = apply("fused_rmsnorm_qkv", _fused_qkv, hidden,
                            norm_weight, self.q_proj.weight,
                            self.k_proj.weight, self.v_proj.weight)
            q = q.reshape([B, T, -1, self.head_dim])
            k = k.reshape([B, T, -1, self.head_dim])
            v = v.reshape([B, T, -1, self.head_dim])
        else:
            q = self.q_proj(hidden).reshape([B, T, -1, self.head_dim])
            k = self.k_proj(hidden).reshape([B, T, -1, self.head_dim])
            v = self.v_proj(hidden).reshape([B, T, -1, self.head_dim])

        if isinstance(cache, PagedKVCache) and T == 1 \
                and jnp.ndim(position_offset) == 1 and attn_mask is None:
            from ..distributed.mesh import get_mesh
            from ..distributed.parallel_layers import manual_axis
            from ..kernels.fusion import fusion_enabled

            # the kernel consumes the whole pool through the block
            # table; under a live mesh (GSPMD sharded pools / manual-mp
            # shard_map) it has no partitioning rule, so serve those
            # from the unfused gather path below
            if fusion_enabled() and get_mesh() is None \
                    and manual_axis("mp")[0] is None:
                # fused decode hot path: RoPE + pool scatter + block
                # gather + split-K attention in one kernel (XLA
                # fallback off-TPU) — models/generation.py's paged
                # decode step pins the mode via serving_fusion()
                bt = cache.block_table
                offs = jnp.asarray(position_offset)

                def _fused_decode(qv, kv, vv, kp, vp, *scales):
                    from ..kernels.paged_attention import fused_paged_decode

                    ks, vs = scales if scales else (None, None)
                    return fused_paged_decode(qv, kv, vv, kp, vp, bt,
                                              offs, cos, sin,
                                              k_scale=ks, v_scale=vs,
                                              kv_cache_dtype=cache.kv_dtype)

                if cache.kv_dtype is not None:
                    # quantized pools: the kernel scatter-quantizes the
                    # new token's row and returns updated scale sidecars
                    out, k_pool, v_pool, k_sc, v_sc = apply(
                        "fused_paged_attention", _fused_decode, q, k, v,
                        Tensor(cache.k), Tensor(cache.v),
                        Tensor(cache.k_scale), Tensor(cache.v_scale))
                    new_cache = PagedKVCache(
                        k_pool._value, v_pool._value, bt,
                        k_sc._value, v_sc._value, kv_dtype=cache.kv_dtype)
                else:
                    out, k_pool, v_pool = apply(
                        "fused_paged_attention", _fused_decode, q, k, v,
                        Tensor(cache.k), Tensor(cache.v))
                    new_cache = PagedKVCache(k_pool._value, v_pool._value,
                                             bt)
                out = out.reshape([B, T, -1])
                return self.o_proj(out), new_cache

        def _rope_fn(xv):
            from ..core.flags import flag

            # the fused kernel takes a scalar offset; per-sequence vector
            # offsets (continuous-batching decode) use the gather path
            if flag("use_pallas_kernels") and jax.default_backend() == "tpu" \
                    and not jnp.ndim(position_offset):
                from ..kernels.rope import fused_rope

                return fused_rope(xv, cos, sin, position_offset)
            return apply_rope(xv, cos, sin, position_offset)
        q = apply("rope", _rope_fn, q)
        k = apply("rope", _rope_fn, k)

        if isinstance(cache, PagedKVCache):
            # serving decode (T == 1) or a chunked-prefill chunk (T ==
            # chunk size): position_offset is a [B] vector of
            # per-sequence frontiers.  Write the chunk's k/v into each
            # sequence's blocks, then attend over the gathered block
            # views — all fixed shapes, one executable forever.  When
            # attn_mask is given it is the [B, T] WRITE-VALIDITY mask of
            # a padded chunk: padded positions scatter into the reserved
            # garbage block 0 instead of a live block, and causal
            # masking hides them from attention (their rope/score junk
            # is never read by a real query).
            bs = cache.k.shape[1]
            bt = cache.block_table
            offsets = jnp.asarray(position_offset)
            pos = offsets[:, None] + jnp.arange(T)          # [B, T]
            wmask = None
            if attn_mask is not None:
                m = attn_mask._value if isinstance(attn_mask, Tensor) \
                    else attn_mask
                wmask = jnp.asarray(m).astype(bool)         # [B, T]

            def _scatter(pool, new):
                # pool [nb, bs, kvh, hd]; new [B, T, kvh, hd] → flat row
                # index block_table[b, pos//bs]*bs + pos%bs per position.
                # The column clamp keeps padded positions past the table
                # width in range (their write is already redirected to
                # garbage by wmask before it could land anywhere real).
                nb = pool.shape[0]
                rows = jnp.arange(bt.shape[0])[:, None]
                col = jnp.minimum(pos // bs, bt.shape[1] - 1)
                idx = bt[rows, col] * bs + pos % bs         # [B, T]
                if wmask is not None:
                    idx = jnp.where(wmask, idx, 0)
                flat = pool.reshape(nb * bs, pool.shape[2], pool.shape[3])
                flat = flat.at[idx.reshape(-1)].set(
                    new.reshape(-1, new.shape[2],
                                new.shape[3]).astype(pool.dtype))
                return flat.reshape(pool.shape)

            k_sc = v_sc = None
            if cache.kv_dtype is not None:
                # quantize-at-write: codes and per-row scales scatter
                # through the SAME flat index (padded rows land their
                # code+scale in garbage block 0, masked from attention)
                def _scatter_q(pool, scales, new):
                    from ..kernels.kv_quant import quantize_kv

                    nb = pool.shape[0]
                    rows = jnp.arange(bt.shape[0])[:, None]
                    col = jnp.minimum(pos // bs, bt.shape[1] - 1)
                    idx = bt[rows, col] * bs + pos % bs     # [B, T]
                    if wmask is not None:
                        idx = jnp.where(wmask, idx, 0)
                    codes, sc = quantize_kv(new, cache.kv_dtype)
                    flat = pool.reshape(nb * bs, pool.shape[2],
                                        pool.shape[3])
                    flat = flat.at[idx.reshape(-1)].set(
                        codes.reshape(-1, codes.shape[2], codes.shape[3]))
                    sflat = scales.reshape(nb * bs).at[
                        idx.reshape(-1)].set(sc.reshape(-1))
                    return flat.reshape(pool.shape), \
                        sflat.reshape(scales.shape)

                k_pool, k_sc = apply("paged_kv_update_quant", _scatter_q,
                                     Tensor(cache.k),
                                     Tensor(cache.k_scale), k)
                v_pool, v_sc = apply("paged_kv_update_quant", _scatter_q,
                                     Tensor(cache.v),
                                     Tensor(cache.v_scale), v)
                new_cache = PagedKVCache(k_pool._value, v_pool._value,
                                         bt, k_sc._value, v_sc._value,
                                         kv_dtype=cache.kv_dtype)
            else:
                k_pool = apply("paged_kv_update", _scatter,
                               Tensor(cache.k), k)
                v_pool = apply("paged_kv_update", _scatter,
                               Tensor(cache.v), v)
                new_cache = PagedKVCache(k_pool._value, v_pool._value, bt)

            if T > 1:
                from ..distributed.mesh import get_mesh
                from ..distributed.parallel_layers import manual_axis
                from ..kernels.fusion import fusion_enabled

                # same mesh caveat as the fused decode intercept: the
                # kernel reads the whole pool through the block table
                if fusion_enabled() and get_mesh() is None \
                        and manual_axis("mp")[0] is None:
                    # fused chunked-prefill hot path: block gather +
                    # causal mask + online softmax + context in one
                    # kernel (XLA fallback off-TPU) — the #1 candidate
                    # mined by analysis/fusionminer on the fused
                    # prefill trace
                    def _fused_chunk(qv, kp, vp, *scales):
                        from ..kernels.chunked_prefill import \
                            fused_chunked_attention

                        ks, vs = scales if scales else (None, None)
                        return fused_chunked_attention(
                            qv, kp, vp, bt, offsets, k_scale=ks,
                            v_scale=vs, kv_cache_dtype=cache.kv_dtype)

                    chunk_args = (q, k_pool, v_pool)
                    if cache.kv_dtype is not None:
                        chunk_args += (k_sc, v_sc)
                    out = apply("fused_chunked_attention", _fused_chunk,
                                *chunk_args)
                    out = out.reshape([B, T, -1])
                    return self.o_proj(out), new_cache

            def _paged_attn(qv, kp, vp, *scales):
                # contiguous per-sequence views of the block pool: the
                # same full-buffer masked attention as the static cache,
                # just gathered through the block table (quantized
                # pools dequantize the gathered copy — this is the
                # unfused parity oracle for the fused kernels)
                kb, vb = kp[bt], vp[bt]         # [B, nbs, bs, kvh, hd]
                if cache.kv_dtype is not None:
                    from ..kernels.kv_quant import decode_codes

                    ksc, vsc = scales
                    kb = (decode_codes(kb, cache.kv_dtype)
                          * ksc[bt][..., None, None]).astype(qv.dtype)
                    vb = (decode_codes(vb, cache.kv_dtype)
                          * vsc[bt][..., None, None]).astype(qv.dtype)
                kb = kb.reshape(bt.shape[0], -1, kp.shape[2],
                                kp.shape[3])
                vb = vb.reshape(bt.shape[0], -1, vp.shape[2],
                                vp.shape[3])
                rep = qv.shape[2] // kb.shape[2]
                if rep > 1:
                    kb = jnp.repeat(kb, rep, axis=2)
                    vb = jnp.repeat(vb, rep, axis=2)
                scores = jnp.einsum(
                    "bthd,bshd->bhts", qv, kb,
                    preferred_element_type=jnp.float32)
                scores = scores / math.sqrt(self.head_dim)
                q_pos = pos                                 # [B, T]
                k_pos = jnp.arange(kb.shape[1])
                valid = k_pos[None, None, :] <= q_pos[:, :, None]
                scores = jnp.where(valid[:, None], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1).astype(qv.dtype)
                return jnp.einsum("bhts,bshd->bthd", probs, vb)

            attn_args = (q, k_pool, v_pool)
            if cache.kv_dtype is not None:
                attn_args += (k_sc, v_sc)
            out = apply("paged_attention", _paged_attn, *attn_args)
            out = out.reshape([B, T, -1])
            return self.o_proj(out), new_cache

        if isinstance(cache, StaticKVCache):
            # fixed-size buffer write; one compiled program per decode
            def _upd(buf, new):
                return jax.lax.dynamic_update_slice(
                    buf, new.astype(buf.dtype), (0, position_offset, 0, 0))

            k_buf = apply("kv_cache_update", _upd, Tensor(cache.k), k)
            v_buf = apply("kv_cache_update", _upd, Tensor(cache.v), v)
            new_cache = StaticKVCache(k_buf._value, v_buf._value)
            max_len = cache.k.shape[1]

            def _static_attn(qv, kb, vb):
                # attend over the full buffer, masking positions beyond
                # the write frontier (and future positions within this
                # chunk, for multi-token prefill into the buffer)
                rep = qv.shape[2] // kb.shape[2]
                if rep > 1:
                    kb = jnp.repeat(kb, rep, axis=2)
                    vb = jnp.repeat(vb, rep, axis=2)
                scores = jnp.einsum(
                    "bthd,bshd->bhts", qv, kb,
                    preferred_element_type=jnp.float32)
                scores = scores / math.sqrt(self.head_dim)
                q_pos = position_offset + jnp.arange(qv.shape[1])
                k_pos = jnp.arange(max_len)
                valid = k_pos[None, :] <= q_pos[:, None]  # [T, max_len]
                scores = jnp.where(valid[None, None], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1).astype(qv.dtype)
                return jnp.einsum("bhts,bshd->bthd", probs, vb)

            out = apply("static_cache_attention", _static_attn, q, k_buf,
                        v_buf)
            out = out.reshape([B, T, -1])
            return self.o_proj(out), new_cache

        if cache is not None:
            from ..ops.manipulation import concat

            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
            new_cache = (k, v)
        else:
            new_cache = None

        # ALWAYS causal with bottom-right alignment: query row i sees keys
        # up to i + (Tk - Tq).  Covers no-cache training (Tk == Tq), cached
        # prefill (past == 0, so plain causal — the old `causal = cache is
        # None` made cached prefill bidirectional, corrupting generation),
        # and single-token decode (row 0 sees all past keys).
        causal = True

        cp = getattr(self.config, "context_parallel", "")
        if cp and cache is None:
            # sequence-parallel full-sequence attention: ring rotates KV
            # shards over the `sp` axis, Ulysses re-shards heads with
            # all-to-alls.  Both resolve the active mesh themselves and
            # fall back to dense attention when there is no `sp` axis —
            # that fallback IS the CPU parity path.
            def _cp_attn(qv, kv, vv):
                from ..distributed.mesh import get_mesh

                m = get_mesh()
                baxis = "data" if (m is not None
                                   and "data" in m.shape) else None
                if cp == "ulysses":
                    from ..kernels.ulysses_attention import ulysses_attention

                    return ulysses_attention(qv, kv, vv, causal=causal,
                                             batch_axis=baxis)
                from ..kernels.ring_attention import ring_attention

                return ring_attention(qv, kv, vv, causal=causal,
                                      batch_axis=baxis)

            out = apply("context_parallel_attention", _cp_attn, q, k, v)
            out = out.reshape([B, T, -1])
            return self.o_proj(out)

        def _attn(qv, kv, vv):
            from ..core.flags import flag
            from ..kernels.flash_attention import (_attn_reference,
                                                   flash_attention_bthd)

            if self.config.use_flash_attention and flag("use_pallas_kernels") \
                    and jax.default_backend() == "tpu":
                return flash_attention_bthd(qv, kv, vv, causal=causal)
            # reference path with GQA repeat
            rep = qv.shape[2] // kv.shape[2]
            if rep > 1:
                kv = jnp.repeat(kv, rep, axis=2)
                vv = jnp.repeat(vv, rep, axis=2)
            qt = jnp.swapaxes(qv, 1, 2)
            kt = jnp.swapaxes(kv, 1, 2)
            vt = jnp.swapaxes(vv, 1, 2)
            out = _attn_reference(qt, kt, vt, causal,
                                  1.0 / math.sqrt(self.head_dim))
            return jnp.swapaxes(out, 1, 2)

        out = apply("attention", _attn, q, k, v)
        out = out.reshape([B, T, -1])
        out = self.o_proj(out)
        if cache is not None:
            return out, new_cache
        return out


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        self.gate_proj = ColumnParallelLinear(h, m, has_bias=False,
                                              gather_output=False)
        self.up_proj = ColumnParallelLinear(h, m, has_bias=False,
                                            gather_output=False)
        self.down_proj = RowParallelLinear(m, h, has_bias=False,
                                           input_is_parallel=True)

    def forward(self, x, norm_weight=None, norm_eps=None):
        if norm_weight is not None:
            # fused serving epilogue: the post-attention RMSNorm folds
            # into gate/up's matmul prologue (row scale computed once),
            # and silu rides as gate's epilogue
            def _fused(xv, nw, wg, wu, wd):
                from ..kernels.fused_norm_linear import (fused_norm_linear,
                                                         rms_scale)

                rs = rms_scale(xv, norm_eps)
                g = fused_norm_linear(xv, rs, nw, wg, activation="silu")
                u = fused_norm_linear(xv, rs, nw, wu)
                return jnp.dot(g * u, wd.astype(g.dtype))

            return apply("fused_rmsnorm_mlp", _fused, x,
                         norm_weight, self.gate_proj.weight,
                         self.up_proj.weight, self.down_proj.weight)
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaMoEMLP(nn.Layer):
    """Top-k routed mixture-of-experts MLP (GShard capacity-padded
    dispatch through kernels/moe_dispatch).

    Stacked expert weights: w_gate/w_up [E, h, m], w_down [E, m, h] —
    the leading expert dim shards on the canonical `expert` mesh axis
    (distributed.sharding moe_* roles); the router is a few KiB and
    stays replicated.  Routing: softmax over router logits, lax.top_k,
    then a running-count capacity-slot assignment; choices past the
    expert's capacity C = ceil(cf*T*K/E) get slot >= C and are dropped
    by dispatch/combine (the GShard contract).
    """

    def __init__(self, config: LlamaConfig):
        super().__init__()
        from ..nn import initializer as I

        h, m = config.hidden_size, config.intermediate_size
        E = config.moe_num_experts
        self.num_experts = E
        self.top_k = config.moe_top_k
        self.capacity_factor = config.moe_capacity_factor
        self.router = nn.Linear(h, E, bias_attr=False)
        std = 1.0 / math.sqrt(h)
        init = I.Normal(std=std)
        self.w_gate = self.create_parameter([E, h, m],
                                            default_initializer=init)
        self.w_up = self.create_parameter([E, h, m],
                                          default_initializer=init)
        self.w_down = self.create_parameter(
            [E, m, h], default_initializer=I.Normal(std=1.0 / math.sqrt(m)))

    def forward(self, x):
        from ..kernels.moe_dispatch import (moe_capacity, moe_combine,
                                            moe_dispatch)

        E, K, cf = self.num_experts, self.top_k, self.capacity_factor
        logits = self.router(x)  # [B, T, E]

        def _moe(xv, lg, wg, wu, wd):
            B, T, H = xv.shape
            n_tok = B * T
            C = moe_capacity(n_tok, E, K, cf)
            tokens = xv.reshape(n_tok, H)
            probs = jax.nn.softmax(
                lg.reshape(n_tok, E).astype(jnp.float32), axis=-1)
            gate, eidx = jax.lax.top_k(probs, K)       # [n_tok, K]
            gate = (gate / jnp.maximum(gate.sum(-1, keepdims=True),
                                       1e-9)).astype(xv.dtype)
            eidx = eidx.astype(jnp.int32)
            # capacity slot per routed choice: running count of earlier
            # choices bound to the same expert (t-major, k-minor
            # priority); overflow (slot >= C) is dropped downstream
            flat_e = eidx.reshape(-1)
            oh = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)
            pos = jnp.cumsum(oh, axis=0) - oh
            sidx = jnp.take_along_axis(pos, flat_e[:, None], axis=1)[:, 0]
            sidx = sidx.reshape(n_tok, K).astype(jnp.int32)
            disp = moe_dispatch(tokens, eidx, sidx, jnp.ones_like(gate),
                                E, C)                  # [E, C, H]
            g = jnp.einsum("ech,ehm->ecm", disp, wg.astype(disp.dtype))
            u = jnp.einsum("ech,ehm->ecm", disp, wu.astype(disp.dtype))
            act = (jax.nn.silu(g.astype(jnp.float32)).astype(disp.dtype)
                   * u)
            eo = jnp.einsum("ecm,emh->ech", act, wd.astype(disp.dtype))
            out = moe_combine(eo, eidx, sidx, gate)    # [n_tok, H]
            return out.reshape(B, T, H)

        return apply("moe_mlp", _moe, x, logits, self.w_gate, self.w_up,
                     self.w_down)


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = LlamaRMSNorm(config.hidden_size,
                                            config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = LlamaRMSNorm(config.hidden_size,
                                                     config.rms_norm_eps)
        self.mlp = (LlamaMoEMLP(config)
                    if getattr(config, "moe_num_experts", 0) > 0
                    else LlamaMLP(config))

    def _fuse_epilogues(self, cache):
        """Fold RMSNorms into the following projections?  Serving-only
        (cache present), and only when the projections run as plain
        local matmuls: fused_norm_linear consumes the raw weights, so
        any mesh sharding annotation or manual-mp collective the
        ColumnParallelLinear forward would have applied must be absent.
        MoE routes through stacked expert weights — not this shape."""
        if cache is None:
            return False
        from ..kernels.fusion import fusion_enabled

        if not fusion_enabled():
            return False
        from ..distributed.mesh import get_mesh
        from ..distributed.parallel_layers import manual_axis

        if get_mesh() is not None or manual_axis("mp")[0] is not None:
            return False
        return isinstance(self.mlp, LlamaMLP)

    def forward(self, hidden, cos, sin, attn_mask=None, cache=None,
                position_offset=0):
        fuse_epi = self._fuse_epilogues(cache)
        residual = hidden
        if cache is not None:
            if fuse_epi:
                h, new_cache = self.self_attn(
                    hidden, cos, sin, attn_mask, cache, position_offset,
                    norm_weight=self.input_layernorm.weight,
                    norm_eps=self.input_layernorm._epsilon)
            else:
                h, new_cache = self.self_attn(
                    self.input_layernorm(hidden), cos, sin, attn_mask,
                    cache, position_offset)
        else:
            h = self.self_attn(self.input_layernorm(hidden), cos, sin,
                               attn_mask)
            new_cache = None
        hidden = residual + h
        residual = hidden
        if fuse_epi:
            h = self.mlp(
                hidden,
                norm_weight=self.post_attention_layernorm.weight,
                norm_eps=self.post_attention_layernorm._epsilon)
        else:
            h = self.mlp(self.post_attention_layernorm(hidden))
        hidden = residual + h
        if cache is not None:
            return hidden, new_cache
        return hidden


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = LlamaRMSNorm(config.hidden_size, config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = precompute_rope(head_dim, config.max_position_embeddings,
                                   config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)
        if config.dtype == "bfloat16":
            self.bfloat16()

    def forward(self, input_ids, attn_mask=None, caches=None,
                position_offset=0):
        hidden = self.embed_tokens(input_ids)
        if self.config.sequence_parallel:
            from ..distributed.sharding import shard_tensor

            hidden = shard_tensor(hidden, placements=[None, "sp", None])
        cos, sin = self.rope_cos._value, self.rope_sin._value
        new_caches = [] if caches is not None else None
        for i, layer in enumerate(self.layers):
            if caches is not None:
                hidden, c = layer(hidden, cos, sin, attn_mask, caches[i],
                                  position_offset)
                new_caches.append(c)
            elif self.config.recompute:
                from ..distributed.recompute import recompute

                hidden = recompute(layer, hidden, cos, sin, attn_mask)
            else:
                hidden = layer(hidden, cos, sin, attn_mask)
        hidden = self.norm(hidden)
        if caches is not None:
            return hidden, new_caches
        return hidden


def _fused_causal_lm_loss(hidden, w, labels, *, w_is_vocab_major, chunk):
    """Next-token cross-entropy computed per token-chunk so the full
    [tokens, vocab] logits never live in HBM.  lax.scan over chunks; each
    chunk's lm-head matmul + logsumexp runs under jax.checkpoint so the
    backward recomputes the chunk logits instead of saving them.

    Replaces the reference's softmax_with_cross_entropy over full logits
    (/root/reference/paddle/fluid/operators/softmax_with_cross_entropy_op.cu)
    with the memory-lean TPU formulation.
    """
    h = hidden[:, :-1]
    lab = labels[:, 1:].astype(jnp.int32)
    B, T, H = h.shape
    n_tok = B * T
    hf = h.reshape(n_tok, H)
    labf = lab.reshape(n_tok)
    n_chunks = max(1, -(-n_tok // chunk))
    csize = -(-n_tok // n_chunks)
    pad = n_chunks * csize - n_tok
    if pad:
        hf = jnp.pad(hf, ((0, pad), (0, 0)))
        labf = jnp.pad(labf, (0, pad), constant_values=-1)
    hs = hf.reshape(n_chunks, csize, H)
    labs = labf.reshape(n_chunks, csize)
    wt = w.T if w_is_vocab_major else w  # [H, V]

    def chunk_nll(h_c, lab_c, wt):
        logits = jnp.einsum("td,dv->tv", h_c, wt.astype(h_c.dtype),
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, jnp.maximum(lab_c, 0)[:, None], axis=-1)[:, 0]
        valid = (lab_c >= 0).astype(jnp.float32)
        return jnp.sum((lse - picked) * valid)

    def body(tot, xs):
        h_c, lab_c = xs
        return tot + jax.checkpoint(chunk_nll)(h_c, lab_c, wt), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (hs, labs))
    return total / n_tok


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
            if config.dtype == "bfloat16":
                self.lm_head.bfloat16()

    def forward(self, input_ids, labels=None, attn_mask=None, caches=None,
                position_offset=0):
        if caches is not None:
            hidden, new_caches = self.model(input_ids, attn_mask, caches,
                                            position_offset)
        else:
            hidden = self.model(input_ids, attn_mask)
        if labels is not None and self.config.fused_lm_loss:
            w = (self.model.embed_tokens.weight
                 if self.config.tie_word_embeddings else self.lm_head.weight)
            loss = apply(
                "fused_causal_lm_loss", _fused_causal_lm_loss, hidden, w,
                labels, w_is_vocab_major=self.config.tie_word_embeddings,
                chunk=self.config.lm_loss_chunk)
            return loss, None
        if self.config.tie_word_embeddings:
            def _tied(h, w):
                return h @ w.T.astype(h.dtype)
            logits = apply("lm_head_tied", _tied, hidden,
                           self.model.embed_tokens.weight)
        else:
            logits = self.lm_head(hidden)
        if labels is not None:
            def _loss(lg, lab):
                lg = lg[:, :-1].astype(jnp.float32)
                lab = lab[:, 1:]
                logp = jax.nn.log_softmax(lg, axis=-1)
                picked = jnp.take_along_axis(
                    logp, lab[..., None].astype(jnp.int32), axis=-1)[..., 0]
                return -jnp.mean(picked)
            loss = apply("causal_lm_loss", _loss, logits, labels)
            return loss, logits
        if caches is not None:
            return logits, new_caches
        return logits

    # --------------------------------------------------------- generation
    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k: Optional[int] = None, top_p: float = 1.0,
                 do_sample: Optional[bool] = None, num_beams: int = 1,
                 eos_token_id: Optional[int] = None, seed=None,
                 use_static_cache: bool = False, stop_sequences=None,
                 tokenizer=None):
        """Decode with the KV cache (models/generation.py): greedy,
        temperature/top-k/top-p sampling, or beam search.

        Back-compat: temperature==0.0 means greedy (the old contract);
        otherwise sampling is on unless do_sample=False."""
        from ..core.dispatch import no_grad_ctx
        from .generation import generate as _generate

        if temperature == 0.0:
            # the documented greedy contract wins over do_sample=True
            do_sample = False
            temperature = 1.0
        if do_sample is None:
            do_sample = True
        if do_sample and num_beams > 1:
            raise ValueError(
                "sampling + beam search is not supported; pass "
                "do_sample=False (or temperature=0.0) with num_beams>1")
        with no_grad_ctx():
            return _generate(
                self, input_ids, max_new_tokens=max_new_tokens,
                do_sample=do_sample, temperature=temperature,
                top_k=top_k or 0, top_p=top_p, num_beams=num_beams,
                eos_token_id=eos_token_id, seed=seed,
                use_static_cache=use_static_cache,
                stop_sequences=stop_sequences, tokenizer=tokenizer)
