# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Pipeline-parallel Llama: functional per-stage forward for the compiled
1F1B schedule.

Reference: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
runs Llama-style models as a PipelineLayer of per-rank sublayers with P2P
send/recv; shared embeddings sync grads across stages (SharedLayerDesc).
TPU-native: the decoder stack is extracted into pp-stacked functional params
([S, L/S, ...] leaves) and driven by distributed.pipeline.pipeline_1f1b —
embedding lives in stage 0's branch, final-norm + lm-head + loss in stage
S-1's, tied-embedding grads are summed by the schedule's closing psum.

The functional math mirrors models/llama.py layer-for-layer (RMSNorm in
f32, rotary on q/k, GQA repeat, SwiGLU MLP) so pp>=2 losses match the eager
single-device model bit-for-bit up to reduction order.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..kernels.flash_attention import _attn_reference
from .llama import LlamaConfig, apply_rope, precompute_rope

__all__ = ["extract_pipeline_params", "make_llama_stage_fn",
           "llama_1f1b_step_fn", "LlamaForCausalLMPipe"]


def extract_pipeline_params(model):
    """Split a LlamaForCausalLM into (shared, per-layer-stacked) pytrees.

    shared: embed / final norm / lm head (absent when tied).
    stacked: each decoder-layer weight stacked over the layer axis [L, ...].
    """
    def layer_leaves(layer):
        a, m = layer.self_attn, layer.mlp
        return {
            "in_ln": layer.input_layernorm.weight._value,
            "q": a.q_proj.weight._value,
            "k": a.k_proj.weight._value,
            "v": a.v_proj.weight._value,
            "o": a.o_proj.weight._value,
            "post_ln": layer.post_attention_layernorm.weight._value,
            "gate": m.gate_proj.weight._value,
            "up": m.up_proj.weight._value,
            "down": m.down_proj.weight._value,
        }

    per_layer = [layer_leaves(l) for l in model.model.layers]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *per_layer)
    shared = {
        "embed": model.model.embed_tokens.weight._value,
        "norm": model.model.norm.weight._value,
    }
    if not model.config.tie_word_embeddings:
        shared["head"] = model.lm_head.weight._value
    return shared, stacked


def load_pipeline_params(model, shared, stacked):
    """Write updated functional params back into the eager model."""
    model.model.embed_tokens.weight.set_value(shared["embed"])
    model.model.norm.weight.set_value(shared["norm"])
    if not model.config.tie_word_embeddings:
        model.lm_head.weight.set_value(shared["head"])
    for i, layer in enumerate(model.model.layers):
        a, m = layer.self_attn, layer.mlp
        layer.input_layernorm.weight.set_value(stacked["in_ln"][i])
        a.q_proj.weight.set_value(stacked["q"][i])
        a.k_proj.weight.set_value(stacked["k"][i])
        a.v_proj.weight.set_value(stacked["v"][i])
        a.o_proj.weight.set_value(stacked["o"][i])
        layer.post_attention_layernorm.weight.set_value(
            stacked["post_ln"][i])
        m.gate_proj.weight.set_value(stacked["gate"][i])
        m.up_proj.weight.set_value(stacked["up"][i])
        m.down_proj.weight.set_value(stacked["down"][i])


def _use_pallas(cfg: LlamaConfig) -> bool:
    from ..core.flags import flag

    return bool(cfg.use_flash_attention and flag("use_pallas_kernels")
                and jax.default_backend() == "tpu")


def _rms(x, w, eps, use_pallas=False):
    if use_pallas:
        from ..kernels.rms_norm import rms_norm as pallas_rms

        return pallas_rms(x, w, eps)
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _decoder_layer(h, lp, cos, sin, cfg: LlamaConfig, use_pallas=False):
    """Functional mirror of models/llama.py LlamaDecoderLayer.forward,
    including its flag-gated Pallas dispatch (flash attention + fused
    RMSNorm on TPU, reference math elsewhere)."""
    B, T = h.shape[0], h.shape[1]
    n_h = cfg.num_attention_heads
    n_kv = cfg.num_key_value_heads
    hd = cfg.hidden_size // n_h
    eps = cfg.rms_norm_eps

    x = _rms(h, lp["in_ln"], eps, use_pallas)
    q = (x @ lp["q"]).reshape(B, T, n_h, hd)
    k = (x @ lp["k"]).reshape(B, T, n_kv, hd)
    v = (x @ lp["v"]).reshape(B, T, n_kv, hd)
    if use_pallas:
        from ..kernels.rope import fused_rope

        q = fused_rope(q, cos, sin)
        k = fused_rope(k, cos, sin)
    else:
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
    if use_pallas:
        from ..kernels.flash_attention import flash_attention_bthd

        attn = flash_attention_bthd(q, k, v, causal=True)
    else:
        rep = n_h // n_kv
        if rep > 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        attn = _attn_reference(qt, kt, vt, True, 1.0 / math.sqrt(hd))
        attn = jnp.swapaxes(attn, 1, 2)
    attn = attn.reshape(B, T, n_h * hd)
    h = h + attn @ lp["o"]

    x2 = _rms(h, lp["post_ln"], eps, use_pallas)
    mlp = (jax.nn.silu(x2 @ lp["gate"]) * (x2 @ lp["up"])) @ lp["down"]
    return h + mlp


def make_llama_stage_fn(cfg: LlamaConfig, n_stages: int):
    """Build stage_fn(stage, shared, local, x, tokens, labels) for
    pipeline_1f1b.  local leaves are [L/S, ...] per-stage layer stacks."""
    hd = cfg.hidden_size // cfg.num_attention_heads
    cos, sin = precompute_rope(hd, cfg.max_position_embeddings,
                               cfg.rope_theta)
    use_pallas = _use_pallas(cfg)

    def stage_fn(stage, shared, local, x, tokens, labels):
        h = jax.lax.cond(
            stage == 0,
            lambda: shared["embed"][tokens].astype(x.dtype),
            lambda: x)

        def body(hh, lp):
            return _decoder_layer(hh, lp, cos, sin, cfg, use_pallas), None

        h, _ = jax.lax.scan(body, h, local)

        def loss_branch():
            hn = _rms(h, shared["norm"], cfg.rms_norm_eps, use_pallas)
            if cfg.tie_word_embeddings:
                logits = hn @ shared["embed"].T.astype(hn.dtype)
            else:
                logits = hn @ shared["head"]
            lg = logits[:, :-1].astype(jnp.float32)
            lab = labels[:, 1:]
            logp = jax.nn.log_softmax(lg, axis=-1)
            picked = jnp.take_along_axis(
                logp, lab[..., None].astype(jnp.int32), axis=-1)[..., 0]
            return -jnp.mean(picked)

        loss = jax.lax.cond(stage == n_stages - 1, loss_branch,
                            lambda: jnp.float32(0.0))
        return h, loss

    return stage_fn


# ---------------------------------------------------------------------------
# LlamaForCausalLMPipe — Llama as a PipelineLayer for the PUBLIC fleet API
# (fleet.distributed_model → PipelineParallel.train_batch → compiled 1F1B).
# The decoder blocks reuse the eager LlamaDecoderLayer, whose Column/Row
# parallel projections are mp-sharded; inside the compiled pipeline's
# shard_map the 1F1B builder hands each pp stage mp-LOCAL weight shards and
# the TP layers emit explicit collectives (manual_collective_axes), so
# pp×mp×dp compose in ONE program — the reference's 4-axis
# HybridCommunicateGroup layout (topology.py:133) with PipelineLayer
# segmentation (pp_layers.py:159).
# ---------------------------------------------------------------------------


def _make_pipe_classes():
    from .. import nn
    from ..core.tensor import Tensor
    from ..distributed.parallel_layers import (ColumnParallelLinear,
                                               VocabParallelEmbedding)
    from .llama import LlamaRMSNorm

    class EmbeddingPipe(nn.Layer):
        def __init__(self, cfg):
            super().__init__()
            self.embed_tokens = VocabParallelEmbedding(
                cfg.vocab_size, cfg.hidden_size)
            self._dtype_str = cfg.dtype
            if cfg.dtype == "bfloat16":
                self.bfloat16()

        def forward(self, ids):
            h = self.embed_tokens(ids)
            if self._dtype_str == "bfloat16":
                h = h.astype("bfloat16")
            return h

    class DecoderPipe(nn.Layer):
        def __init__(self, cfg):
            super().__init__()
            from .llama import LlamaDecoderLayer

            self.layer = LlamaDecoderLayer(cfg)
            hd = cfg.hidden_size // cfg.num_attention_heads
            cos, sin = precompute_rope(hd, cfg.max_position_embeddings,
                                       cfg.rope_theta)
            self.register_buffer("rope_cos", Tensor(cos), persistable=False)
            self.register_buffer("rope_sin", Tensor(sin), persistable=False)
            if cfg.dtype == "bfloat16":
                self.bfloat16()

        def forward(self, h):
            return self.layer(h, self.rope_cos._value, self.rope_sin._value)

    class HeadPipe(nn.Layer):
        def __init__(self, cfg):
            super().__init__()
            self.norm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
            self.lm_head = ColumnParallelLinear(
                cfg.hidden_size, cfg.vocab_size, has_bias=False,
                gather_output=True)
            if cfg.dtype == "bfloat16":
                self.bfloat16()

        def forward(self, h):
            return self.lm_head(self.norm(h))

    return EmbeddingPipe, DecoderPipe, HeadPipe


def _llama_pipe_loss(logits, labels):
    """Next-token shift + cross entropy, matching LlamaForCausalLM's
    labels=... path (llama.py loss: logits[:, :-1] vs labels[:, 1:])."""
    from ..nn import functional as F

    vocab = logits.shape[-1]
    lg = logits[:, :-1].reshape([-1, vocab])
    lab = labels[:, 1:].reshape([-1])
    return F.cross_entropy(lg, lab)


def LlamaForCausalLMPipe(cfg: LlamaConfig, num_stages: Optional[int] = None):
    """Build Llama as a PipelineLayer: [embedding] + decoder blocks +
    [final-norm + lm-head], loss_fn = shifted cross entropy.  Pass to
    fleet.distributed_model under a pp (optionally ×mp×dp) mesh."""
    from ..distributed.pipeline import PipelineLayer

    EmbeddingPipe, DecoderPipe, HeadPipe = _make_pipe_classes()
    layers = ([EmbeddingPipe(cfg)]
              + [DecoderPipe(cfg) for _ in range(cfg.num_hidden_layers)]
              + [HeadPipe(cfg)])
    return PipelineLayer(layers, num_stages=num_stages,
                         loss_fn=_llama_pipe_loss)


def llama_1f1b_step_fn(cfg: LlamaConfig, mesh, n_microbatches: int,
                       micro_batch: int, seq_len: int,
                       axis_name: str = "pp",
                       data_axis: Optional[str] = None):
    """Return step(shared, stacked_S, tokens, labels) ->
    (loss, g_stacked_S, g_shared), jit-ready.

    stacked_S leaves are [S, L/S, ...] (reshape the [L, ...] stacks from
    extract_pipeline_params).  tokens/labels: [M, micro, seq] microbatched;
    with data_axis set, micro is the GLOBAL microbatch size (sharded over
    that axis).
    """
    from ..distributed.pipeline import pipeline_1f1b

    S = mesh.shape[axis_name]
    stage_fn = make_llama_stage_fn(cfg, S)
    dp = mesh.shape.get(data_axis, 1) if data_axis else 1
    local_micro = micro_batch // dp
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    act_example = jnp.zeros((local_micro, seq_len, cfg.hidden_size), dtype)

    def step(shared, stacked, tokens, labels):
        return pipeline_1f1b(stage_fn, stacked, shared, tokens, labels,
                             act_example, mesh=mesh, axis_name=axis_name,
                             data_axis=data_axis)

    return step
