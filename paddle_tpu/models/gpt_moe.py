# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""MoE transformer LM — the expert-parallel pretrain config
(BASELINE.md config 4: ERNIE-4.5-MoE / DeepSeek-V2 style).

DeepSeek-V2 recipe: dense first layer(s), then MoE FFNs with shared experts
alongside routed experts; GQA attention; RMSNorm.  Built from the Llama
attention stack + distributed.moe.MoELayer so routing rides the ep mesh
axis (reference analog: incubate MoELayer + global_scatter/gather ops).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..core.dispatch import apply
from ..distributed.moe import MoELayer
from ..nn import functional as F
from .llama import (LlamaAttention, LlamaConfig, LlamaMLP, LlamaRMSNorm,
                    precompute_rope)
from ..core.tensor import Tensor


@dataclass
class MoEConfig:
    vocab_size: int = 102400
    hidden_size: int = 2048
    intermediate_size: int = 5632
    moe_intermediate_size: int = 1408
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    num_experts: int = 64
    num_shared_experts: int = 2
    top_k: int = 6
    first_dense_layers: int = 1
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    dtype: str = "bfloat16"

    @staticmethod
    def tiny(**overrides):
        cfg = MoEConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            moe_intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, num_experts=4,
            num_shared_experts=1, top_k=2, first_dense_layers=1,
            max_position_embeddings=128, dtype="float32")
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg

    def _as_llama(self) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            num_key_value_heads=self.num_key_value_heads,
            max_position_embeddings=self.max_position_embeddings,
            rms_norm_eps=self.rms_norm_eps, rope_theta=self.rope_theta,
            dtype=self.dtype, use_flash_attention=self.dtype == "bfloat16")


class MoEDecoderLayer(nn.Layer):
    def __init__(self, config: MoEConfig, use_moe: bool):
        super().__init__()
        lcfg = config._as_llama()
        self.input_layernorm = LlamaRMSNorm(config.hidden_size,
                                            config.rms_norm_eps)
        self.self_attn = LlamaAttention(lcfg)
        self.post_attention_layernorm = LlamaRMSNorm(config.hidden_size,
                                                     config.rms_norm_eps)
        self.use_moe = use_moe
        if use_moe:
            self.moe = MoELayer(
                d_model=config.hidden_size,
                d_hidden=config.moe_intermediate_size,
                num_experts=config.num_experts, top_k=config.top_k,
                capacity_factor=config.capacity_factor, gate="gshard",
                activation="silu")
            if config.num_shared_experts > 0:
                shared_cfg = config._as_llama()
                shared_cfg.intermediate_size = (config.moe_intermediate_size
                                                * config.num_shared_experts)
                self.shared_expert = LlamaMLP(shared_cfg)
            else:
                self.shared_expert = None
        else:
            self.mlp = LlamaMLP(lcfg)

    def forward(self, hidden, cos, sin):
        residual = hidden
        h = self.self_attn(self.input_layernorm(hidden), cos, sin)
        hidden = residual + h
        residual = hidden
        h = self.post_attention_layernorm(hidden)
        if self.use_moe:
            routed = self.moe(h)
            if self.shared_expert is not None:
                routed = routed + self.shared_expert(h)
            h = routed
        else:
            h = self.mlp(h)
        return residual + h


class MoEForCausalLM(nn.Layer):
    def __init__(self, config: MoEConfig):
        super().__init__()
        self.config = config
        from ..distributed.parallel_layers import (ColumnParallelLinear,
                                                   VocabParallelEmbedding)

        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        self.layers = nn.LayerList([
            MoEDecoderLayer(config, use_moe=i >= config.first_dense_layers)
            for i in range(config.num_hidden_layers)])
        self.norm = LlamaRMSNorm(config.hidden_size, config.rms_norm_eps)
        self.lm_head = ColumnParallelLinear(config.hidden_size,
                                            config.vocab_size, has_bias=False)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = precompute_rope(head_dim, config.max_position_embeddings,
                                   config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)
        if config.dtype == "bfloat16":
            self.bfloat16()

    def forward(self, input_ids, labels=None):
        hidden = self.embed_tokens(input_ids)
        cos, sin = self.rope_cos._value, self.rope_sin._value
        aux_total = None
        for layer in self.layers:
            hidden = layer(hidden, cos, sin)
            if layer.use_moe and layer.moe.aux_loss is not None:
                a = layer.moe.aux_loss
                aux_total = a if aux_total is None else aux_total + a
        hidden = self.norm(hidden)
        logits = self.lm_head(hidden)
        if labels is not None:
            def _loss(lg, lab):
                import jax

                lg = lg[:, :-1].astype(jnp.float32)
                lab = lab[:, 1:]
                logp = jax.nn.log_softmax(lg, axis=-1)
                picked = jnp.take_along_axis(
                    logp, lab[..., None].astype(jnp.int32), axis=-1)[..., 0]
                return -jnp.mean(picked)

            lm_loss = apply("moe_lm_loss", _loss, logits, labels)
            if aux_total is not None:
                lm_loss = lm_loss + self.config.aux_loss_weight * aux_total
            return lm_loss, logits
        return logits
