# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Diffusion UNet with cross-attention (BASELINE.md config 5: SDXL UNet via
the inference predictor).

Compact UNet2DConditionModel: timestep sinusoidal embedding + MLP, ResNet
blocks (GroupNorm/SiLU), down/up sampling, and transformer blocks with
self + cross attention over text context — the ppdiffusers UNet structure,
sized by config.  Serving path: jit.save → inference.Predictor.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Tuple

import jax
import jax.numpy as jnp

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn import functional as F


@dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 2048
    attention_head_dim: int = 64
    transformer_layers_per_block: Tuple[int, ...] = (1, 2, 10)
    norm_num_groups: int = 32
    dtype: str = "float32"

    @staticmethod
    def tiny(**overrides):
        cfg = UNetConfig(
            in_channels=4, out_channels=4, block_out_channels=(32, 64),
            layers_per_block=1, cross_attention_dim=32, attention_head_dim=8,
            transformer_layers_per_block=(1, 1), norm_num_groups=8)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


def timestep_embedding(timesteps, dim, max_period=10000.0):
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = timesteps.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class ResnetBlock(nn.Layer):
    def __init__(self, in_c, out_c, temb_dim, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(min(groups, in_c), in_c)
        self.conv1 = nn.Conv2D(in_c, out_c, 3, padding=1)
        self.time_emb_proj = nn.Linear(temb_dim, out_c)
        self.norm2 = nn.GroupNorm(min(groups, out_c), out_c)
        self.conv2 = nn.Conv2D(out_c, out_c, 3, padding=1)
        self.skip = nn.Conv2D(in_c, out_c, 1) if in_c != out_c else None

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        t = self.time_emb_proj(F.silu(temb))
        h = h + t.unsqueeze(-1).unsqueeze(-1)
        h = self.conv2(F.silu(self.norm2(h)))
        if self.skip is not None:
            x = self.skip(x)
        return x + h


class CrossAttnBlock(nn.Layer):
    """Spatial transformer: self-attn + cross-attn + geglu FFN."""

    def __init__(self, channels, n_layers, ctx_dim, head_dim, groups):
        super().__init__()
        self.norm = nn.GroupNorm(min(groups, channels), channels)
        self.proj_in = nn.Linear(channels, channels)
        heads = max(channels // head_dim, 1)
        self.blocks = nn.LayerList()
        for _ in range(n_layers):
            blk = nn.LayerDict({
                "norm1": nn.LayerNorm(channels),
                "attn1": nn.MultiHeadAttention(channels, heads),
                "norm2": nn.LayerNorm(channels),
                "attn2": nn.MultiHeadAttention(channels, heads,
                                               kdim=ctx_dim, vdim=ctx_dim),
                "norm3": nn.LayerNorm(channels),
                "ff1": nn.Linear(channels, channels * 4),
                "ff2": nn.Linear(channels * 4, channels),
            })
            self.blocks.append(blk)
        self.proj_out = nn.Linear(channels, channels)

    def forward(self, x, context):
        B, C, H, W = x.shape
        residual = x
        h = self.norm(x)
        from ..ops.manipulation import reshape, transpose

        h = transpose(reshape(h, [B, C, H * W]), [0, 2, 1])  # [B, HW, C]
        h = self.proj_in(h)
        for blk in self.blocks:
            h = h + blk["attn1"](blk["norm1"](h))
            h = h + blk["attn2"](blk["norm2"](h), context, context)
            h = h + blk["ff2"](F.gelu(blk["ff1"](blk["norm3"](h))))
        h = self.proj_out(h)
        h = reshape(transpose(h, [0, 2, 1]), [B, C, H, W])
        return h + residual


class UNet2DConditionModel(nn.Layer):
    def __init__(self, config: UNetConfig):
        super().__init__()
        self.config = config
        ch = config.block_out_channels
        temb_dim = ch[0] * 4
        g = config.norm_num_groups
        self.time_embed = nn.Sequential(
            nn.Linear(ch[0], temb_dim), nn.Silu(), nn.Linear(temb_dim,
                                                             temb_dim))
        self.conv_in = nn.Conv2D(config.in_channels, ch[0], 3, padding=1)

        self.down_res = nn.LayerList()
        self.down_attn = nn.LayerList()
        self.downsamplers = nn.LayerList()
        in_c = ch[0]
        skip_chs = [ch[0]]  # conv_in output
        for i, out_c in enumerate(ch):
            for j in range(config.layers_per_block):
                self.down_res.append(ResnetBlock(in_c, out_c, temb_dim, g))
                self.down_attn.append(CrossAttnBlock(
                    out_c, config.transformer_layers_per_block[i],
                    config.cross_attention_dim, config.attention_head_dim, g)
                    if i > 0 else nn.Identity())
                in_c = out_c
                skip_chs.append(out_c)
            if i < len(ch) - 1:
                self.downsamplers.append(
                    nn.Conv2D(out_c, out_c, 3, stride=2, padding=1))
                skip_chs.append(out_c)

        self.mid_res1 = ResnetBlock(in_c, in_c, temb_dim, g)
        self.mid_attn = CrossAttnBlock(
            in_c, config.transformer_layers_per_block[-1],
            config.cross_attention_dim, config.attention_head_dim, g)
        self.mid_res2 = ResnetBlock(in_c, in_c, temb_dim, g)

        self.up_res = nn.LayerList()
        self.up_attn = nn.LayerList()
        self.upsamplers = nn.LayerList()
        rev = list(reversed(ch))
        for i, out_c in enumerate(rev):
            for j in range(config.layers_per_block + 1):
                skip_c = skip_chs.pop()
                self.up_res.append(ResnetBlock(in_c + skip_c, out_c, temb_dim,
                                               g))
                self.up_attn.append(CrossAttnBlock(
                    out_c, config.transformer_layers_per_block[
                        len(ch) - 1 - i],
                    config.cross_attention_dim, config.attention_head_dim, g)
                    if (len(ch) - 1 - i) > 0 else nn.Identity())
                in_c = out_c
            if i < len(rev) - 1:
                self.upsamplers.append(nn.Conv2D(out_c, out_c, 3, padding=1))

        self.conv_norm_out = nn.GroupNorm(min(g, ch[0]), ch[0])
        self.conv_out = nn.Conv2D(ch[0], config.out_channels, 3, padding=1)
        if config.dtype != "float32":
            self.astype(config.dtype)

    def forward(self, sample, timestep, encoder_hidden_states):
        cfg = self.config
        # sinusoid computed in f32 for precision, then cast to whatever
        # dtype the weights actually hold (cfg.dtype, a later .bfloat16()
        # or .half() — all routes change the parameter dtype)
        wdt = self.time_embed[0].weight._value.dtype
        temb = apply("timestep_embed",
                     lambda t: timestep_embedding(
                         t, cfg.block_out_channels[0]).astype(wdt),
                     timestep, _differentiable=False)
        temb = self.time_embed(temb)

        h = self.conv_in(sample)
        skips = [h]
        idx = 0
        for i, out_c in enumerate(cfg.block_out_channels):
            for j in range(cfg.layers_per_block):
                h = self.down_res[idx](h, temb)
                attn = self.down_attn[idx]
                if not isinstance(attn, nn.Identity):
                    h = attn(h, encoder_hidden_states)
                skips.append(h)
                idx += 1
            if i < len(cfg.block_out_channels) - 1:
                h = self.downsamplers[i](h)
                skips.append(h)

        h = self.mid_res1(h, temb)
        h = self.mid_attn(h, encoder_hidden_states)
        h = self.mid_res2(h, temb)

        from ..ops.manipulation import concat

        idx = 0
        for i in range(len(cfg.block_out_channels)):
            for j in range(cfg.layers_per_block + 1):
                skip = skips.pop()
                h = concat([h, skip], axis=1)
                h = self.up_res[idx](h, temb)
                attn = self.up_attn[idx]
                if not isinstance(attn, nn.Identity):
                    h = attn(h, encoder_hidden_states)
                idx += 1
            if i < len(cfg.block_out_channels) - 1:
                h = F.interpolate(h, scale_factor=2, mode="nearest")
                h = self.upsamplers[i](h)

        h = F.silu(self.conv_norm_out(h))
        return self.conv_out(h)
