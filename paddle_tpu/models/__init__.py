"""Model zoo beyond vision: LLM/MoE/diffusion families (BASELINE configs 2-5)."""
from .llama import (LlamaConfig, LlamaDecoderLayer, LlamaForCausalLM,  # noqa: F401
                    LlamaModel)
from .bert import (BertConfig, BertForPretraining,  # noqa: F401
                   BertForSequenceClassification, BertModel)
from .gpt_moe import MoEConfig, MoEForCausalLM  # noqa: F401
from .unet import UNet2DConditionModel, UNetConfig  # noqa: F401
from . import generation  # noqa: F401
from .generation import generate  # noqa: F401
