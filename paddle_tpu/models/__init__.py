"""Model zoo beyond vision: LLM families (BASELINE.md configs 2-4)."""
from .llama import (LlamaConfig, LlamaDecoderLayer, LlamaForCausalLM,  # noqa: F401
                    LlamaModel)
