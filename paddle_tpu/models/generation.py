# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Autoregressive decoding over KV caches (reference capability:
paddle/fluid/operators/fused/fused_multi_transformer_op.cu decode path +
the sampling ops top_k_op/top_p_sampling; the high-level loop lives in
PaddleNLP's GenerationMixin, whose API this mirrors).

Works with any causal LM exposing the cache contract
``model(input_ids, caches=..., position_offset=...) -> (logits, caches)``
with per-layer (k, v) tuples that grow by concat (models/llama.py).
The token loop runs on host (one compiled step per shape, like eager
serving); each step's math is jit-compiled by XLA.
"""
from __future__ import annotations

import weakref

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor


def _cache_dims(model):
    """(kv_heads, head_dim, dtype) shared by both cache layouts."""
    cfg = model.config
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    kv_heads = getattr(cfg, "num_key_value_heads", None) \
        or cfg.num_attention_heads
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    return kv_heads, head_dim, dtype


def _empty_caches(model, batch):
    kv_heads, head_dim, dtype = _cache_dims(model)
    empty = jnp.zeros((batch, 0, kv_heads, head_dim), dtype)
    return [(Tensor(empty), Tensor(empty))
            for _ in range(model.config.num_hidden_layers)]


def _static_caches(model, batch, max_len):
    """Fixed-size caches: every decode step reuses ONE set of op shapes
    (the concat-growing cache changes shapes per token, recompiling each
    step on TPU — see models/llama.py StaticKVCache)."""
    from .llama import StaticKVCache

    kv_heads, head_dim, dtype = _cache_dims(model)
    return [StaticKVCache.empty(batch, max_len, kv_heads, head_dim, dtype)
            for _ in range(model.config.num_hidden_layers)]


def _select_token(logits, *, do_sample, temperature, top_k, top_p, key):
    """logits: [B, V] fp32 -> token ids [B]."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    if temperature and temperature != 1.0:
        logits = logits / temperature
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p (keep the first token
        # crossing the threshold)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def _gather_caches(caches, idx):
    return [(Tensor(c[0]._value[idx]), Tensor(c[1]._value[idx]))
            for c in caches]


def _weights_fingerprint(model):
    """Identity fingerprint of every parameter buffer.  Any rebind of a
    param's backing array (optimizer step, set_state_dict, checkpoint
    load) changes the tuple, invalidating decode steps that captured the
    old weights as jit constants (ADVICE r2: a stale compiled step would
    otherwise silently serve pre-update weights).

    Held as WEAKREFS, not id()s: CPython reuses freed addresses, and
    set_state_dict frees each old array right before allocating its
    same-sized replacement, so an id tuple can collide with the cached
    one and keep serving pre-update weights (ADVICE r3).  A weakref to a
    freed array returns None and can never match; holding the refs does
    not extend the old arrays' lifetime."""
    return tuple(weakref.ref(p._value) for p in model.parameters())


def _fingerprint_matches(model, fp):
    if fp is None:
        return False
    params = model.parameters()
    return len(fp) == len(params) and all(
        r() is p._value for r, p in zip(fp, params))


def make_decode_step(model):
    """One jit-compiled single-token decode step over static caches.

    Returns step(tok[B,1] int32, caches, offset int32 scalar) ->
    (last_logits[B,V] f32, new_caches).  The token position rides in as a
    TRACED scalar and the caches are fixed-size, so every decode step of
    every generation with the same (B, max_len) hits ONE executable —
    the TPU serving property the reference gets from
    fused_multi_transformer's decode kernel.  Model weights are captured
    as jit constants (inference: they never change under the trace).

    The wrapper is cached ON THE MODEL keyed by a weights fingerprint:
    jax.jit's own cache then holds one executable per (B, max_len) across
    generate() calls — a fresh wrapper per call would retrace + recompile
    the whole transformer every request, while an un-fingerprinted one
    would keep serving stale weights after training/set_state_dict."""
    step = getattr(model, "_decode_step", None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, "_decode_step_fp", None)):
        return step
    fp = _weights_fingerprint(model)

    from .llama import StaticKVCache

    from ..core.dispatch import no_grad_ctx

    @jax.jit
    def step(tok, caches, offset):
        with no_grad_ctx():
            wrapped = [StaticKVCache(k, v) for k, v in caches]
            logits, new_caches = model(Tensor(tok), caches=wrapped,
                                       position_offset=offset)
            return (logits._value[:, -1].astype(jnp.float32),
                    [(c.k, c.v) for c in new_caches])

    model._decode_step = step
    model._decode_step_fp = fp
    return step


def make_beam_decode_step(model):
    """Beam-search decode step over static caches: re-indexes the
    preallocated caches by `parents` on the batch*beam axis INSIDE the
    compiled program, then decodes one token (reference semantics:
    BeamSearchDecoder's gather of cell states, fluid/layers/rnn.py, over
    fused_multi_transformer's fixed CacheKV).  step(tok[BV,1], caches,
    offset, parents[BV]) -> (logits[BV,V] f32, new_caches)."""
    step = getattr(model, "_beam_decode_step", None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, "_beam_decode_step_fp", None)):
        return step
    fp = _weights_fingerprint(model)

    from .llama import StaticKVCache

    from ..core.dispatch import no_grad_ctx

    @jax.jit
    def step(tok, caches, offset, parents):
        with no_grad_ctx():
            wrapped = [StaticKVCache(k[parents], v[parents])
                       for k, v in caches]
            logits, new_caches = model(Tensor(tok), caches=wrapped,
                                       position_offset=offset)
            return (logits._value[:, -1].astype(jnp.float32),
                    [(c.k, c.v) for c in new_caches])

    model._beam_decode_step = step
    model._beam_decode_step_fp = fp
    return step


def generate(model, input_ids, max_new_tokens=32, do_sample=False,
             temperature=1.0, top_k=0, top_p=1.0, num_beams=1,
             eos_token_id=None, seed=None, use_static_cache=False):
    """Decode continuations for a batch of prompts.

    Returns [B, T_prompt + T_new] token ids (beam search returns the best
    beam per batch element).  Greedy by default; ``do_sample`` enables
    temperature/top-k/top-p sampling; ``num_beams > 1`` switches to beam
    search with length-agnostic log-prob scores."""
    from ..core.dispatch import no_grad_ctx
    from ..ops import random as rnd

    ids = np.asarray(input_ids.numpy() if hasattr(input_ids, "numpy")
                     else input_ids)
    if ids.ndim == 1:
        ids = ids[None]
    B, T0 = ids.shape
    max_pos = getattr(model.config, "max_position_embeddings", None)
    if max_pos is not None and T0 + max_new_tokens > max_pos:
        raise ValueError(
            f"prompt ({T0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_position_embeddings ({max_pos}) — the rope table has no "
            f"entries past it (dynamic_slice would silently clamp)")
    with no_grad_ctx():
        if num_beams > 1:
            return _beam_generate(model, ids, max_new_tokens, num_beams,
                                  eos_token_id,
                                  use_static_cache=use_static_cache)
        # seed=None draws from the framework RNG stream (paddle.seed)
        key = rnd.next_key() if seed is None else jax.random.PRNGKey(seed)
        caches = _static_caches(model, B, T0 + max_new_tokens) \
            if use_static_cache else _empty_caches(model, B)
        logits, caches = model(to_tensor(ids.astype(np.int32)),
                               caches=caches, position_offset=0)
        decode_step = None
        if use_static_cache:
            decode_step = make_decode_step(model)
            cache_arrays = [(c.k, c.v) for c in caches]
        out = [ids]
        finished = np.zeros((B,), bool)
        last = logits._value[:, -1].astype(jnp.float32)
        for step in range(max_new_tokens):
            key, sub = jax.random.split(key)
            tok = _select_token(last, do_sample=do_sample,
                                temperature=temperature, top_k=top_k,
                                top_p=top_p, key=sub)
            tok_np = np.asarray(tok)
            if eos_token_id is not None:
                tok_np = np.where(finished, eos_token_id, tok_np)
                finished |= tok_np == eos_token_id
            out.append(tok_np[:, None])
            if eos_token_id is not None and finished.all():
                break
            if step == max_new_tokens - 1:
                break  # the last token is chosen; don't pay one more step
            cur_raw = tok_np[:, None].astype(np.int32)
            if decode_step is not None:
                # one compiled program for the whole generation: the
                # position is a traced scalar, the caches fixed-size
                last, cache_arrays = decode_step(
                    cur_raw, cache_arrays, np.int32(T0 + step))
            else:
                logits, caches = model(to_tensor(cur_raw), caches=caches,
                                       position_offset=T0 + step)
                last = logits._value[:, -1].astype(jnp.float32)
        return to_tensor(np.concatenate(out, axis=1))


def _beam_generate(model, ids, max_new_tokens, beams, eos_token_id,
                   use_static_cache=False):
    B, T0 = ids.shape
    BV = B * beams
    # prefill once per prompt, then replicate caches across beams
    caches = _static_caches(model, B, T0 + max_new_tokens) \
        if use_static_cache else _empty_caches(model, B)
    logits, caches = model(to_tensor(ids.astype(np.int32)), caches=caches,
                           position_offset=0)
    rep = jnp.repeat(jnp.arange(B), beams)
    beam_step = None
    if use_static_cache:
        beam_step = make_beam_decode_step(model)
        # replicate the fixed-size buffers across beams; per-step gathers
        # then happen inside the compiled step
        cache_arrays = [(c.k[rep], c.v[rep]) for c in caches]
    else:
        caches = _gather_caches(caches, rep)
    last = jnp.repeat(logits._value[:, -1].astype(jnp.float32), beams,
                      axis=0)                      # [B*beams, V]
    scores = jnp.tile(jnp.asarray([0.0] + [-1e9] * (beams - 1)), (B,))
    tokens_acc = []     # list of [B*beams] arrays
    parents_acc = []
    finished = jnp.zeros((BV,), bool)
    V = last.shape[-1]
    end_only = None
    if eos_token_id is not None:
        end_only = jnp.full((V,), -1e9).at[eos_token_id].set(0.0)
    for step in range(max_new_tokens):
        logp = jax.nn.log_softmax(last, axis=-1)
        if end_only is not None:
            logp = jnp.where(finished[:, None], end_only, logp)
        total = (scores[:, None] + logp).reshape(B, beams * V)
        top_scores, top_idx = jax.lax.top_k(total, beams)   # [B, beams]
        parents = (top_idx // V + jnp.arange(B)[:, None] * beams).reshape(-1)
        toks = (top_idx % V).reshape(-1)
        scores = top_scores.reshape(-1)
        if beam_step is None:
            caches = _gather_caches(caches, parents)
        if eos_token_id is not None:
            finished = finished[parents] | (toks == eos_token_id)
        tokens_acc.append(np.asarray(toks))
        parents_acc.append(np.asarray(parents))
        if eos_token_id is not None and bool(finished.all()):
            break
        if step == max_new_tokens - 1:
            break  # the last token is chosen; don't pay one more step
        cur_raw = np.asarray(toks)[:, None].astype(np.int32)
        if beam_step is not None:
            # cache re-indexing by `parents` happens inside the compiled
            # step: one executable serves the whole beam generation
            last, cache_arrays = beam_step(
                cur_raw, cache_arrays, np.int32(T0 + step),
                np.asarray(parents))
        else:
            logits, caches = model(to_tensor(cur_raw), caches=caches,
                                   position_offset=T0 + step)
            last = logits._value[:, -1].astype(jnp.float32)
    # backtrace best beam (beam 0 holds the max score after top_k)
    T = len(tokens_acc)
    seq = np.zeros((BV, T), np.int64)
    cursor = np.arange(BV)
    for t in range(T - 1, -1, -1):
        seq[:, t] = tokens_acc[t][cursor]
        cursor = parents_acc[t][cursor]
    best = seq.reshape(B, beams, T)[:, 0]
    return to_tensor(np.concatenate([ids, best], axis=1))
