# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Autoregressive decoding over KV caches (reference capability:
paddle/fluid/operators/fused/fused_multi_transformer_op.cu decode path +
the sampling ops top_k_op/top_p_sampling; the high-level loop lives in
PaddleNLP's GenerationMixin, whose API this mirrors).

Works with any causal LM exposing the cache contract
``model(input_ids, caches=..., position_offset=...) -> (logits, caches)``
with per-layer (k, v) tuples that grow by concat (models/llama.py).
The token loop runs on host (one compiled step per shape, like eager
serving); each step's math is jit-compiled by XLA.
"""
from __future__ import annotations

import functools
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor


def _cache_dims(model):
    """(kv_heads, head_dim, dtype) shared by both cache layouts."""
    cfg = model.config
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    kv_heads = getattr(cfg, "num_key_value_heads", None) \
        or cfg.num_attention_heads
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    return kv_heads, head_dim, dtype


def _empty_caches(model, batch):
    kv_heads, head_dim, dtype = _cache_dims(model)
    empty = jnp.zeros((batch, 0, kv_heads, head_dim), dtype)
    return [(Tensor(empty), Tensor(empty))
            for _ in range(model.config.num_hidden_layers)]


def _static_caches(model, batch, max_len):
    """Fixed-size caches: every decode step reuses ONE set of op shapes
    (the concat-growing cache changes shapes per token, recompiling each
    step on TPU — see models/llama.py StaticKVCache).

    Under an ACTIVE mesh executor the [batch, max_len, kv_heads,
    head_dim] buffers are committed sharded on the tp axis over
    kv_heads — the same layout the serving path gives the paged pool
    (``MeshExecutor.kv_pool_spec``) — instead of replicating an entire
    max_len cache onto every chip.  ``clean_spec`` inside ``put`` falls
    back to replication when kv_heads does not divide tp."""
    from .llama import StaticKVCache

    kv_heads, head_dim, dtype = _cache_dims(model)
    caches = [StaticKVCache.empty(batch, max_len, kv_heads, head_dim,
                                  dtype)
              for _ in range(model.config.num_hidden_layers)]
    from ..distributed.executor import current_executor

    ex = current_executor()
    if ex is not None:
        spec = ex.static_kv_spec()
        for c in caches:
            c.k = ex.put(c.k, spec)
            c.v = ex.put(c.v, spec)
    return caches


def _select_token(logits, *, do_sample, temperature, top_k, top_p, key):
    """logits: [B, V] fp32 -> token ids [B]."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    if temperature and temperature != 1.0:
        logits = logits / temperature
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p (keep the first token
        # crossing the threshold)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def _gather_caches(caches, idx):
    return [(Tensor(c[0]._value[idx]), Tensor(c[1]._value[idx]))
            for c in caches]


# ---------------------------------------------------------------------------
# decode-step registry (serving hot loop + analysis H106)
# ---------------------------------------------------------------------------
# Every compiled step built here registers its raw (pre-jit) Python
# function so paddle_tpu.analysis.hazards can AST-audit the serving hot
# loop (H106: host syncs / python branching inside a decode step force a
# device→host round trip per token).  Weak refs: a registered step must
# not keep its model alive after the caller drops it.
_decode_step_registry: "list[tuple[weakref.ref, str]]" = []


def register_decode_step(fn, kind: str = "decode"):
    """Register ``fn`` (the raw Python function behind a compiled decode/
    prefill step) for hazard auditing and jaxpr X-ray (analysis.xray
    resolves abstract arg shapes per ``kind``).  Returns ``fn`` so it
    can be used as a decorator."""
    _decode_step_registry.append((weakref.ref(fn), kind))
    return fn


def registered_decode_steps():
    """Live registered decode-step functions (dead models pruned)."""
    return [fn for fn, _kind in registered_decode_step_entries()]


def registered_decode_step_entries():
    """Live ``(fn, kind)`` registry entries — the X-ray audit uses the
    kind to build each step's abstract argument shapes."""
    alive = []
    remaining = []
    for r, kind in _decode_step_registry:
        fn = r()
        if fn is not None:
            alive.append((fn, kind))
            remaining.append((r, kind))
    _decode_step_registry[:] = remaining
    return alive


# ---------------------------------------------------------------------------
# stop sequences (shared between generate() and serving.Scheduler)
# ---------------------------------------------------------------------------

def normalize_stop_sequences(stop_sequences, tokenizer=None):
    """Normalize user-facing stop specs to ``list[list[int]]``.

    Accepts None, a single token id, one token-id sequence, a list of
    either, or strings (requires ``tokenizer`` with an ``encode`` method
    or a callable returning token ids)."""
    if stop_sequences is None:
        return []
    if isinstance(stop_sequences, (int, np.integer, str)):
        stop_sequences = [stop_sequences]
    elif stop_sequences and all(
            isinstance(t, (int, np.integer)) for t in stop_sequences):
        # one bare token-id sequence
        stop_sequences = [list(stop_sequences)]
    out = []
    for s in stop_sequences:
        if isinstance(s, str):
            if tokenizer is None:
                raise ValueError(
                    "string stop sequences need a tokenizer= with an "
                    "encode method (generate works on token ids)")
            enc = getattr(tokenizer, "encode", tokenizer)
            s = enc(s)
            ids = getattr(s, "ids", s)  # tokenizers-style Encoding
            s = list(np.asarray(ids).reshape(-1))
        elif isinstance(s, (int, np.integer)):
            s = [s]
        s = [int(t) for t in s]
        if not s:
            raise ValueError("empty stop sequence")
        out.append(s)
    return out


def match_stop(generated, stop_sequences) -> bool:
    """True when ``generated`` (token ids, oldest→newest) ends with any
    of the normalized stop sequences.  The serving scheduler and
    ``generate()`` share this exact termination check."""
    for s in stop_sequences:
        n = len(s)
        if n <= len(generated) and list(generated[-n:]) == s:
            return True
    return False


def _weights_fingerprint(model):
    """Identity fingerprint of every parameter buffer.  Any rebind of a
    param's backing array (optimizer step, set_state_dict, checkpoint
    load) changes the tuple, invalidating decode steps that captured the
    old weights as jit constants (ADVICE r2: a stale compiled step would
    otherwise silently serve pre-update weights).

    Held as WEAKREFS, not id()s: CPython reuses freed addresses, and
    set_state_dict frees each old array right before allocating its
    same-sized replacement, so an id tuple can collide with the cached
    one and keep serving pre-update weights (ADVICE r3).  A weakref to a
    freed array returns None and can never match; holding the refs does
    not extend the old arrays' lifetime."""
    return tuple(weakref.ref(p._value) for p in model.parameters())


def _fingerprint_matches(model, fp):
    if fp is None:
        return False
    params = model.parameters()
    return len(fp) == len(params) and all(
        r() is p._value for r, p in zip(fp, params))


def make_decode_step(model):
    """One jit-compiled single-token decode step over static caches.

    Returns step(tok[B,1] int32, caches, offset int32 scalar) ->
    (last_logits[B,V] f32, new_caches).  The token position rides in as a
    TRACED scalar and the caches are fixed-size, so every decode step of
    every generation with the same (B, max_len) hits ONE executable —
    the TPU serving property the reference gets from
    fused_multi_transformer's decode kernel.  Model weights are captured
    as jit constants (inference: they never change under the trace).

    The wrapper is cached ON THE MODEL keyed by a weights fingerprint:
    jax.jit's own cache then holds one executable per (B, max_len) across
    generate() calls — a fresh wrapper per call would retrace + recompile
    the whole transformer every request, while an un-fingerprinted one
    would keep serving stale weights after training/set_state_dict."""
    step = getattr(model, "_decode_step", None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, "_decode_step_fp", None)):
        return step
    fp = _weights_fingerprint(model)

    from .llama import StaticKVCache

    from ..core.dispatch import no_grad_ctx

    @jax.jit
    @functools.partial(register_decode_step, kind="decode")
    def step(tok, caches, offset):
        with no_grad_ctx():
            wrapped = [StaticKVCache(k, v) for k, v in caches]
            logits, new_caches = model(Tensor(tok), caches=wrapped,
                                       position_offset=offset)
            return (logits._value[:, -1].astype(jnp.float32),
                    [(c.k, c.v) for c in new_caches])

    model._decode_step = step
    model._decode_step_fp = fp
    return step


def make_beam_decode_step(model):
    """Beam-search decode step over static caches: re-indexes the
    preallocated caches by `parents` on the batch*beam axis INSIDE the
    compiled program, then decodes one token (reference semantics:
    BeamSearchDecoder's gather of cell states, fluid/layers/rnn.py, over
    fused_multi_transformer's fixed CacheKV).  step(tok[BV,1], caches,
    offset, parents[BV]) -> (logits[BV,V] f32, new_caches)."""
    step = getattr(model, "_beam_decode_step", None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, "_beam_decode_step_fp", None)):
        return step
    fp = _weights_fingerprint(model)

    from .llama import StaticKVCache

    from ..core.dispatch import no_grad_ctx

    @jax.jit
    @functools.partial(register_decode_step, kind="beam_decode")
    def step(tok, caches, offset, parents):
        with no_grad_ctx():
            wrapped = [StaticKVCache(k[parents], v[parents])
                       for k, v in caches]
            logits, new_caches = model(Tensor(tok), caches=wrapped,
                                       position_offset=offset)
            return (logits._value[:, -1].astype(jnp.float32),
                    [(c.k, c.v) for c in new_caches])

    model._beam_decode_step = step
    model._beam_decode_step_fp = fp
    return step


def make_prefill_step(model):
    """One jit-compiled prompt-prefill step over static caches, reusable
    at any padded prompt length (serving buckets prompts to block
    multiples, so the jit cache holds one executable per bucket, never
    per prompt).  step(ids[1, Lp] int32, caches, last_index int32 scalar)
    -> (last_real_logits[1, V] f32, new_caches): the logits are gathered
    at the TRACED index of the last REAL prompt token, so padding never
    changes which row is returned."""
    step = getattr(model, "_prefill_step", None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, "_prefill_step_fp", None)):
        return step
    fp = _weights_fingerprint(model)

    from .llama import StaticKVCache

    from ..core.dispatch import no_grad_ctx

    @jax.jit
    @functools.partial(register_decode_step, kind="prefill")
    def step(ids, caches, last_index):
        with no_grad_ctx():
            wrapped = [StaticKVCache(k, v) for k, v in caches]
            logits, new_caches = model(Tensor(ids), caches=wrapped,
                                       position_offset=0)
            last = jax.lax.dynamic_index_in_dim(
                logits._value, last_index, axis=1, keepdims=False)
            return (last.astype(jnp.float32),
                    [(c.k, c.v) for c in new_caches])

    model._prefill_step = step
    model._prefill_step_fp = fp
    return step


def _wrap_paged(pools, block_tables, kv_dtype):
    """Pool entries -> PagedKVCache views: (k, v) tuples for full-
    precision pools, (k, v, k_scale, v_scale) for quantized ones
    (serving/cache.py BlockKVPool.layers).  Called at TRACE time only —
    the branch is on the build-time kv_dtype constant, never a traced
    value, and lives outside the H106-audited step source."""
    from .llama import PagedKVCache

    if kv_dtype is not None:
        return [PagedKVCache(k, v, block_tables, ks, vs,
                             kv_dtype=kv_dtype)
                for k, v, ks, vs in pools]
    return [PagedKVCache(k, v, block_tables) for k, v in pools]


def _unwrap_paged(caches, kv_dtype):
    """Inverse of :func:`_wrap_paged`: repack updated cache views into
    pool-entry tuples for the engine to rebind."""
    if kv_dtype is not None:
        return [(c.k, c.v, c.k_scale, c.v_scale) for c in caches]
    return [(c.k, c.v) for c in caches]


def _kv_dtype_suffix(kv_dtype):
    """Cache-attr / step-kind suffix: fp32 and quantized engines must
    never share a cached compiled step (their pool treedefs differ, so
    a shared attr would guarantee a retrace on the second engine)."""
    return f"_{kv_dtype}" if kv_dtype is not None else ""


def make_paged_decode_step(model, fused=None, kv_cache_dtype=None):
    """The continuous-batching decode step: one token for a BUCKET of
    sequences, each at its own position, over the shared block-pool
    cache (models/llama.py PagedKVCache).  step(tok[B,1] int32, pools
    [(k, v)] per layer, block_tables[B, max_blocks] int32, lengths[B]
    int32) -> (last_logits[B, V] f32, new_pools).  Every input shape is
    fixed by the engine config, so after the first call this NEVER
    retraces — the property the serving engine asserts every step.

    ``fused`` pins the serving-fusion mode (kernels/fusion) for the
    whole traced program: True forces the fused paged-attention decode
    kernel + RMSNorm epilogues (XLA fallback off-TPU), False forces the
    unfused reference path, None resolves FLAGS_use_fused_serving once
    at build time.  The mode is baked into the trace, so fused and
    unfused steps are distinct cached executables.

    ``kv_cache_dtype`` (None / "int8" / "fp8") selects quantized pool
    entries: pools become [(k, v, k_scale, v_scale)] per layer, writes
    quantize in-trace and reads dequantize at the kernel DMA boundary
    (kernels/kv_quant.py).  Like ``fused``, the dtype is baked into the
    attr/kind so mixed-precision engines over one model never collide
    on a cached step."""
    from ..kernels.fusion import resolve_serving_fusion, serving_fusion
    from ..kernels.kv_quant import resolve_kv_cache_dtype

    fused = resolve_serving_fusion(fused)
    kv_dtype = resolve_kv_cache_dtype(kv_cache_dtype)
    attr = ("_paged_decode_step_fused" if fused
            else "_paged_decode_step") + _kv_dtype_suffix(kv_dtype)
    step = getattr(model, attr, None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, attr + "_fp", None)):
        return step
    fp = _weights_fingerprint(model)

    from ..core.dispatch import no_grad_ctx

    # resolved OUTSIDE the step: its source is AST-audited (H106) and a
    # build-time ternary must not read as per-token Python branching
    kind = ("paged_decode_fused" if fused else "paged_decode") \
        + _kv_dtype_suffix(kv_dtype)

    @jax.jit
    @functools.partial(register_decode_step, kind=kind)
    def step(tok, pools, block_tables, lengths):
        with no_grad_ctx(), serving_fusion(fused):
            wrapped = _wrap_paged(pools, block_tables, kv_dtype)
            logits, new_caches = model(Tensor(tok), caches=wrapped,
                                       position_offset=lengths)
            return (logits._value[:, -1].astype(jnp.float32),
                    _unwrap_paged(new_caches, kv_dtype))

    setattr(model, attr, step)
    setattr(model, attr + "_fp", fp)
    return step


def make_chunked_prefill_step(model, fused=None, kv_cache_dtype=None):
    """Chunked prefill straight into the paged block pool: ONE fixed
    chunk shape serves every prompt length, so prefill compiles O(1)
    programs instead of one per length bucket (each bucket was a new
    fused XLA program — the compile-cost term PAPERS.md's fusion
    analysis quantifies).  step(ids[1, C] int32, pools [(k, v)] per
    layer, block_table[1, max_blocks] int32, start[1] int32,
    last_index int32 scalar) -> (logits[1, V] f32, new_pools).

    The chunk's tokens occupy absolute positions ``start .. start+C-1``
    of the sequence; their k/v land in the pool at block offsets through
    the block table.  ``last_index`` is the TRACED index of the last
    REAL token within the chunk: positions past it are padding, whose
    pool writes the model redirects to the reserved garbage block via
    the validity mask, and whose logits are never returned — the
    gathered row is always the last real one, so the final chunk of a
    prompt yields the first generated token.  Both ``start`` and
    ``last_index`` are traced, so every chunk of every prompt hits the
    SAME executable (the serving engine asserts this via
    ``warn_on_retrace``).

    ``fused`` (see make_paged_decode_step) pins the serving-fusion mode:
    fused prefill folds each RMSNorm into the following projections
    (kernels/fused_norm_linear) and runs the chunk attention through the
    fused block-gather + online-softmax kernel
    (kernels/chunked_prefill — mined by analysis/fusionminer as the #1
    remaining candidate); padded positions still scatter to the garbage
    block and mask off exactly as on the gather path.

    ``kv_cache_dtype`` selects quantized pool entries exactly as in
    :func:`make_paged_decode_step` (padded positions scatter their
    garbage CODES + scale into block 0 the same way)."""
    from ..kernels.fusion import resolve_serving_fusion, serving_fusion
    from ..kernels.kv_quant import resolve_kv_cache_dtype

    fused = resolve_serving_fusion(fused)
    kv_dtype = resolve_kv_cache_dtype(kv_cache_dtype)
    attr = ("_chunked_prefill_step_fused" if fused
            else "_chunked_prefill_step") + _kv_dtype_suffix(kv_dtype)
    step = getattr(model, attr, None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, attr + "_fp", None)):
        return step
    fp = _weights_fingerprint(model)

    from ..core.dispatch import no_grad_ctx

    # see make_paged_decode_step: keep the build-time ternary out of
    # the H106-audited step source
    kind = ("chunked_prefill_fused" if fused else "chunked_prefill") \
        + _kv_dtype_suffix(kv_dtype)

    @jax.jit
    @functools.partial(register_decode_step, kind=kind)
    def step(ids, pools, block_table, start, last_index):
        with no_grad_ctx(), serving_fusion(fused):
            wrapped = _wrap_paged(pools, block_table, kv_dtype)
            valid = (jnp.arange(ids.shape[1]) <= last_index)[None, :]
            logits, new_caches = model(Tensor(ids),
                                       attn_mask=Tensor(valid),
                                       caches=wrapped,
                                       position_offset=start)
            last = jax.lax.dynamic_index_in_dim(
                logits._value, last_index, axis=1, keepdims=False)
            return (last.astype(jnp.float32),
                    _unwrap_paged(new_caches, kv_dtype))

    setattr(model, attr, step)
    setattr(model, attr + "_fp", fp)
    return step


def make_moe_block_step(model):
    """Full-sequence forward of a mixture-of-experts model
    (LlamaConfig.moe_num_experts > 0) — the traced workload behind the
    MoE static-analysis audits.  step(ids[B, T] int32) -> logits
    [B, T, V] f32.  Off-TPU the dispatch/combine kernels resolve to
    their XLA one-hot einsum fallback, so this exact program is what
    CPU tier-1 checks for parity and the analyzers price."""
    step = getattr(model, "_moe_block_step", None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, "_moe_block_step_fp", None)):
        return step
    fp = _weights_fingerprint(model)

    from ..core.dispatch import no_grad_ctx

    @jax.jit
    @functools.partial(register_decode_step, kind="moe_block")
    def step(ids):
        with no_grad_ctx():
            logits = model(Tensor(ids))
            return logits._value.astype(jnp.float32)

    model._moe_block_step = step
    model._moe_block_step_fp = fp
    return step


def make_ring_sp_step(model, mesh=None):
    """Full-sequence forward through the sequence-parallel attention
    path (LlamaConfig.context_parallel = "ring"/"ulysses").  ``mesh``
    (real or abstract) is installed around the traced body via
    distributed.mesh.use_mesh so trace-time mesh resolution sees the
    ``sp`` axis; None keeps whatever mesh is globally active — no `sp`
    axis means the dense fallback, which IS the CPU parity path.
    step(ids[B, T] int32) -> logits[B, T, V] f32."""
    step = getattr(model, "_ring_sp_step", None)
    if step is not None and _fingerprint_matches(
            model, getattr(model, "_ring_sp_step_fp", None)) \
            and getattr(model, "_ring_sp_step_mesh", None) is mesh:
        return step
    fp = _weights_fingerprint(model)

    import contextlib

    from ..core.dispatch import no_grad_ctx
    from ..distributed.mesh import use_mesh

    @jax.jit
    @functools.partial(register_decode_step, kind="ring_sp")
    def step(ids):
        ctx = (use_mesh(mesh) if mesh is not None
               else contextlib.nullcontext())
        with no_grad_ctx(), ctx:
            logits = model(Tensor(ids))
            return logits._value.astype(jnp.float32)

    model._ring_sp_step = step
    model._ring_sp_step_fp = fp
    model._ring_sp_step_mesh = mesh
    return step


def generate(model, input_ids, max_new_tokens=32, do_sample=False,
             temperature=1.0, top_k=0, top_p=1.0, num_beams=1,
             eos_token_id=None, seed=None, use_static_cache=False,
             stop_sequences=None, tokenizer=None, sampling=None):
    """Decode continuations for a batch of prompts.

    Returns [B, T_prompt + T_new] token ids (beam search returns the best
    beam per batch element).  Greedy by default; ``do_sample`` enables
    temperature/top-k/top-p sampling (``sampling=SamplingParams(...)``
    is the equivalent explicit spelling, shared with ``Engine.submit``);
    ``num_beams > 1`` switches to beam search with length-agnostic
    log-prob scores.

    Sampled decoding uses the serving engine's key schedule — the seed's
    base key folded with each TOKEN INDEX (serving/sampling.py) — so the
    same prompt + seed is token-exact here and under the engine, which
    is what extends the engine-vs-generate parity oracle to sampled
    outputs.  All rows of a batch share the base key: identical prompts
    sample identical continuations (seed identity is per REQUEST, not
    per row — submit separate engine requests for diverse samples).

    Termination: a sequence finishes when it emits ``eos_token_id``, when
    its generated suffix matches any of ``stop_sequences`` (token-id
    list(s); strings need ``tokenizer``), or at ``max_new_tokens``.
    Finished sequences are padded with ``eos_token_id`` (0 when only stop
    sequences are given) and the loop exits early once EVERY sequence has
    finished — a mixed-length batch never pays full-length compute."""
    from ..core.dispatch import no_grad_ctx
    from ..ops import random as rnd

    if sampling is not None:
        # lazy: serving imports this module at load time
        from ..serving.sampling import resolve_sampling

        params = resolve_sampling(sampling)
        do_sample = params is not None
        if params is not None:
            temperature, top_k, top_p, seed = (params.temperature,
                                               params.top_k,
                                               params.top_p, params.seed)
    ids = np.asarray(input_ids.numpy() if hasattr(input_ids, "numpy")
                     else input_ids)
    if ids.ndim == 1:
        ids = ids[None]
    B, T0 = ids.shape
    max_pos = getattr(model.config, "max_position_embeddings", None)
    if max_pos is not None and T0 + max_new_tokens > max_pos:
        raise ValueError(
            f"prompt ({T0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_position_embeddings ({max_pos}) — the rope table has no "
            f"entries past it (dynamic_slice would silently clamp)")
    stops = normalize_stop_sequences(stop_sequences, tokenizer)
    with no_grad_ctx():
        if num_beams > 1:
            if stops:
                raise ValueError(
                    "stop_sequences are not supported with beam search; "
                    "use eos_token_id or greedy/sampling decoding")
            return _beam_generate(model, ids, max_new_tokens, num_beams,
                                  eos_token_id,
                                  use_static_cache=use_static_cache)
        # seed=None draws from the framework RNG stream (paddle.seed)
        key = rnd.next_key() if seed is None else jax.random.PRNGKey(seed)
        if do_sample:
            # serving/sampling key schedule: token i samples with
            # fold_in(base, i) on device — slot- and batch-independent,
            # so the engine reproduces these exact streams per seed
            from ..serving.sampling import sample_at

            base_keys = np.broadcast_to(
                np.asarray(key, np.uint32).reshape(-1)[:2], (B, 2))
            s_temps = np.full((B,), float(temperature or 0.0), np.float32)
            s_tks = np.full((B,), int(top_k or 0), np.int32)
            s_tps = np.full((B,), float(top_p if top_p else 1.0),
                            np.float32)
        caches = _static_caches(model, B, T0 + max_new_tokens) \
            if use_static_cache else _empty_caches(model, B)
        logits, caches = model(to_tensor(ids.astype(np.int32)),
                               caches=caches, position_offset=0)
        decode_step = None
        if use_static_cache:
            decode_step = make_decode_step(model)
            cache_arrays = [(c.k, c.v) for c in caches]
        out = [ids]
        finished = np.zeros((B,), bool)
        terminal = eos_token_id is not None or bool(stops)
        # finished rows are padded with eos (0 when only stop sequences
        # terminate) so a mixed-length batch stays rectangular
        pad_id = eos_token_id if eos_token_id is not None else 0
        max_stop = max((len(s) for s in stops), default=0)
        suffixes = [[] for _ in range(B)]   # per-row stop-match windows
        last = logits._value[:, -1].astype(jnp.float32)
        for step in range(max_new_tokens):
            if do_sample:
                tok = sample_at(last, s_temps, s_tks, s_tps, base_keys,
                                np.full((B,), step, np.int32))
            else:
                tok = jnp.argmax(last, axis=-1)
            tok_np = np.asarray(tok)
            if terminal:
                tok_np = np.where(finished, pad_id, tok_np)
                if eos_token_id is not None:
                    finished |= tok_np == eos_token_id
                for b in range(B):
                    if stops and not finished[b]:
                        suffixes[b].append(int(tok_np[b]))
                        if len(suffixes[b]) > max_stop:
                            del suffixes[b][:-max_stop]
                        finished[b] = match_stop(suffixes[b], stops)
            out.append(tok_np[:, None])
            if terminal and finished.all():
                break
            if step == max_new_tokens - 1:
                break  # the last token is chosen; don't pay one more step
            cur_raw = tok_np[:, None].astype(np.int32)
            if decode_step is not None:
                # one compiled program for the whole generation: the
                # position is a traced scalar, the caches fixed-size
                last, cache_arrays = decode_step(
                    cur_raw, cache_arrays, np.int32(T0 + step))
            else:
                logits, caches = model(to_tensor(cur_raw), caches=caches,
                                       position_offset=T0 + step)
                last = logits._value[:, -1].astype(jnp.float32)
        return to_tensor(np.concatenate(out, axis=1))


def _beam_generate(model, ids, max_new_tokens, beams, eos_token_id,
                   use_static_cache=False):
    B, T0 = ids.shape
    BV = B * beams
    # prefill once per prompt, then replicate caches across beams
    caches = _static_caches(model, B, T0 + max_new_tokens) \
        if use_static_cache else _empty_caches(model, B)
    logits, caches = model(to_tensor(ids.astype(np.int32)), caches=caches,
                           position_offset=0)
    rep = jnp.repeat(jnp.arange(B), beams)
    beam_step = None
    if use_static_cache:
        beam_step = make_beam_decode_step(model)
        # replicate the fixed-size buffers across beams; per-step gathers
        # then happen inside the compiled step
        cache_arrays = [(c.k[rep], c.v[rep]) for c in caches]
    else:
        caches = _gather_caches(caches, rep)
    last = jnp.repeat(logits._value[:, -1].astype(jnp.float32), beams,
                      axis=0)                      # [B*beams, V]
    scores = jnp.tile(jnp.asarray([0.0] + [-1e9] * (beams - 1)), (B,))
    tokens_acc = []     # list of [B*beams] arrays
    parents_acc = []
    finished = jnp.zeros((BV,), bool)
    V = last.shape[-1]
    end_only = None
    if eos_token_id is not None:
        end_only = jnp.full((V,), -1e9).at[eos_token_id].set(0.0)
    for step in range(max_new_tokens):
        logp = jax.nn.log_softmax(last, axis=-1)
        if end_only is not None:
            logp = jnp.where(finished[:, None], end_only, logp)
        total = (scores[:, None] + logp).reshape(B, beams * V)
        top_scores, top_idx = jax.lax.top_k(total, beams)   # [B, beams]
        parents = (top_idx // V + jnp.arange(B)[:, None] * beams).reshape(-1)
        toks = (top_idx % V).reshape(-1)
        scores = top_scores.reshape(-1)
        if beam_step is None:
            caches = _gather_caches(caches, parents)
        if eos_token_id is not None:
            finished = finished[parents] | (toks == eos_token_id)
        tokens_acc.append(np.asarray(toks))
        parents_acc.append(np.asarray(parents))
        if eos_token_id is not None and bool(finished.all()):
            break
        if step == max_new_tokens - 1:
            break  # the last token is chosen; don't pay one more step
        cur_raw = np.asarray(toks)[:, None].astype(np.int32)
        if beam_step is not None:
            # cache re-indexing by `parents` happens inside the compiled
            # step: one executable serves the whole beam generation
            last, cache_arrays = beam_step(
                cur_raw, cache_arrays, np.int32(T0 + step),
                np.asarray(parents))
        else:
            logits, caches = model(to_tensor(cur_raw), caches=caches,
                                   position_offset=T0 + step)
            last = logits._value[:, -1].astype(jnp.float32)
    # backtrace best beam (beam 0 holds the max score after top_k)
    T = len(tokens_acc)
    seq = np.zeros((BV, T), np.int64)
    cursor = np.arange(BV)
    for t in range(T - 1, -1, -1):
        seq[:, t] = tokens_acc[t][cursor]
        cursor = parents_acc[t][cursor]
    best = seq.reshape(B, beams, T)[:, 0]
    return to_tensor(np.concatenate([ids, best], axis=1))
