# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""BERT (BASELINE.md config 2: BERT-base pretraining, Fleet data-parallel).

Architecture per the original BERT; built from the framework's transformer
layers so it exercises MultiHeadAttention/TransformerEncoder the way
PaddleNLP's BertModel does (the reference tree itself hosts the nn layers,
python/paddle/nn/layer/transformer.py).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn import functional as F


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12

    @staticmethod
    def base(**overrides):
        cfg = BertConfig()
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg

    @staticmethod
    def tiny(**overrides):
        cfg = BertConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, intermediate_size=128,
                         max_position_embeddings=64, type_vocab_size=2)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size,
                                            config.hidden_size)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size,
                                                  config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        from .. import ops

        T = input_ids.shape[1]
        pos = ops.arange(T, dtype="int32").unsqueeze(0)
        emb = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob,
            layer_norm_eps=config.layer_norm_eps)
        self.encoder = nn.TransformerEncoder(enc_layer,
                                             config.num_hidden_layers)
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        emb = self.embeddings(input_ids, token_type_ids)
        mask = None
        if attention_mask is not None:
            def _expand_mask(m):
                # [B, T] (1 = keep) → additive [B, 1, 1, T]
                return (1.0 - m.astype(jnp.float32))[:, None, None, :] * -1e9
            mask = apply("bert_mask", _expand_mask, attention_mask,
                         _differentiable=False)
        seq = self.encoder(emb, mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.mlm_transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.mlm_norm = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.mlm_bias = self.create_parameter([config.vocab_size],
                                              is_bias=True)
        self.nsp_head = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))

        def _mlm_logits(hv, emb_w, bias):
            return hv @ emb_w.T + bias
        logits = apply("mlm_logits", _mlm_logits, h,
                       self.bert.embeddings.word_embeddings.weight,
                       self.mlm_bias)
        nsp_logits = self.nsp_head(pooled)
        if masked_lm_labels is not None:
            mlm_loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                masked_lm_labels.reshape([-1]), ignore_index=-100)
            total = mlm_loss
            if next_sentence_labels is not None:
                total = total + F.cross_entropy(nsp_logits,
                                                next_sentence_labels)
            return total, logits, nsp_logits
        return logits, nsp_logits


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels), logits
        return logits
