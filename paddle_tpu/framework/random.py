"""RNG state helpers (reference: python/paddle/framework/random.py)."""
from ..ops.random import get_rng_state, set_rng_state, seed  # noqa: F401
