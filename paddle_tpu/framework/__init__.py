"""paddle.framework namespace parity."""
from ..core.dtype import get_default_dtype, set_default_dtype  # noqa: F401
from ..core.tensor import Parameter  # noqa: F401
from .io import load, save  # noqa: F401
from ..ops.random import seed  # noqa: F401
from .random import get_rng_state, set_rng_state  # noqa: F401
