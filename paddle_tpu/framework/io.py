# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:572,788).

Pickles nested state structures with tensors converted to numpy, protocol 4
chunking like the reference.  Async sharded distributed checkpoints live in
paddle_tpu.distributed.checkpoint (orbax-backed).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor


def _tree_to_numpy(obj: Any):
    if isinstance(obj, Tensor):
        return _TensorPayload(obj.numpy())
    if isinstance(obj, dict):
        return {k: _tree_to_numpy(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_tree_to_numpy(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


class _TensorPayload:
    """Marks arrays that were Tensors so load() can rewrap them."""

    __slots__ = ("array",)

    def __init__(self, array):
        self.array = np.asarray(array)


def _tree_from_numpy(obj: Any, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        return obj.array if return_numpy else Tensor(jnp.asarray(obj.array))
    if isinstance(obj, dict):
        return {k: _tree_from_numpy(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_tree_from_numpy(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_tree_to_numpy(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        data = pickle.load(f)
    return _tree_from_numpy(data, return_numpy)
