"""hapi Model (reference: python/paddle/hapi/model.py:907 Model, :1557 fit).

The train loop compiles its step through jit.to_static, so Model.fit trains
with one fused XLA program per batch shape — the reference's dygraph adapter
runs op-by-op instead (model.py:705 DynamicGraphAdapter).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..io import DataLoader, Dataset
from ..metric import Metric
from .. import observability
from .callbacks import CallbackList, LRScheduler, ModelCheckpoint, ProgBarLogger


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False
        self._skip_batch = False
        self._train_step_fn = None
        self._mesh_executor = None

    # ------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, mesh=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        self._build_train_step()
        if mesh is not None:
            self.use_mesh(mesh)

    def use_mesh(self, mesh):
        """Install a ``distributed.MeshExecutor`` (or build one from an
        ``{axis: size}`` dict): params/optimizer slots are laid out per
        the canonical ``SpecLayout`` and the compiled train/eval steps
        run as one GSPMD program per step with explicit shardings +
        donation.  Returns the executor (``reconcile_train`` on it
        audits the compiled program against the static shard plan,
        diagnostic S209)."""
        from ..distributed.executor import as_executor

        ex = as_executor(mesh)
        ex.install(self)
        return ex

    def _build_train_step(self):
        from .. import jit

        network = self.network
        loss_fn = self._loss
        optimizer = self._optimizer

        if optimizer is None or loss_fn is None:
            return

        def train_step(inputs, labels):
            outputs = network(*inputs)
            losses = loss_fn(outputs, *labels)
            losses.backward()
            optimizer.step()
            optimizer.clear_grad()
            return losses, outputs

        # compile accounting over the one entry point fit() drives: a
        # shape-stable loader compiles this exactly once; a churning one
        # shows up in observability.compile_stats() / xla_compiles_total
        self._train_step_fn = observability.track_compiles(
            jit.to_static(train_step), label="hapi::train_step")

        def eval_step(inputs, labels):
            outputs = network(*inputs)
            losses = loss_fn(outputs, *labels)
            return losses, outputs

        self._eval_step_fn = jit.to_static(eval_step)

    # ------------------------------------------------------------- steps
    @staticmethod
    def _split_batch(data):
        if isinstance(data, (list, tuple)):
            if len(data) >= 2:
                return [data[0]], list(data[1:])
            return [data[0]], []
        return [data], []

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        inputs = [to_tensor(x) if not isinstance(x, Tensor) else x
                  for x in inputs]
        labels = [to_tensor(y) if not isinstance(y, Tensor) else y
                  for y in labels]
        if self._mesh_executor is not None:
            # commit batch leaves onto the batch spec so they match the
            # step's in_shardings (to_tensor lands on one device)
            inputs = self._mesh_executor.shard_batch(inputs)
            labels = self._mesh_executor.shard_batch(labels)
        loss, outputs = self._train_step_fn(inputs, labels)
        metrics = [float(np.asarray(loss.numpy()).mean())]
        for m in self._metrics:
            m.update(m.compute(outputs, *labels).numpy())
        return metrics if len(metrics) > 1 else metrics[0]

    def xray(self, inputs, labels=None, *, chip="v5e",
             hbm_budget_bytes=None):
        """Statically X-ray the compiled train step on a sample batch
        (analysis.xray): per-op FLOP/byte roofline, peak-live-HBM from a
        liveness walk, donation/host-callback/f64 hazards.  The report
        lands in ``self.xray_report`` and its FLOPs/bytes/peak-HBM
        mirror into the observability gauges; nothing is executed (one
        abstract trace).  Requires :meth:`prepare` with an optimizer and
        loss."""
        from ..analysis import xray as _xray

        if getattr(self, "_train_step_fn", None) is None:
            raise RuntimeError(
                "Model.xray needs the compiled train step — call "
                "prepare(optimizer, loss) first")
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        inputs = [to_tensor(x) if not isinstance(x, Tensor) else x
                  for x in inputs]
        labels = [to_tensor(y) if not isinstance(y, Tensor) else y
                  for y in labels]
        self.network.train()
        report = _xray.analyze_train_step(
            self._train_step_fn, inputs, labels, chip=chip,
            hbm_budget_bytes=hbm_budget_bytes)
        _xray.export_report_gauges(report)
        self.xray_report = report
        return report

    def shardplan(self, inputs, labels=None, *, request=None):
        """Statically plan the compiled train step on an abstract mesh
        (analysis.shardplan): sharding propagation under a SpecLayout,
        per-chip peak HBM, the implied collective inventory, and
        S205–S208 diagnostics.  ``request`` is an
        ``analysis.PlanRequest`` (None → llama layout on a simulated
        ``(data=2, fsdp=2, tp=2)`` mesh).  The report lands in
        ``self.shardplan_report`` and mirrors into the
        ``shardplan_comm_bytes`` / ``shardplan_per_chip_peak_hbm_bytes``
        gauges; nothing executes and no devices are needed."""
        from ..analysis import shardplan as _shardplan

        if getattr(self, "_train_step_fn", None) is None:
            raise RuntimeError(
                "Model.shardplan needs the compiled train step — call "
                "prepare(optimizer, loss) first")
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        inputs = [to_tensor(x) if not isinstance(x, Tensor) else x
                  for x in inputs]
        labels = [to_tensor(y) if not isinstance(y, Tensor) else y
                  for y in labels]
        self.network.train()
        report = _shardplan.plan_train_step(
            self._train_step_fn, inputs, labels, request=request)
        _shardplan.export_plan_gauges(report)
        self.shardplan_report = report
        return report

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        inputs = [to_tensor(x) for x in inputs]
        labels = [to_tensor(y) for y in labels]
        loss, outputs = self._eval_step_fn(inputs, labels)
        for m in self._metrics:
            m.update(m.compute(outputs, *labels).numpy())
        return float(np.asarray(loss.numpy()).mean())

    def predict_batch(self, inputs):
        from ..core.dispatch import no_grad_ctx

        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad_ctx():
            out = self.network(*[to_tensor(x) for x in inputs])
        return out

    # ------------------------------------------------------------- loops
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None,
            xray_on_start=False, hbm_budget_bytes=None, shardplan=None,
            mesh=None):
        if mesh is not None and self._mesh_executor is None:
            # late mesh install (prepare(mesh=...) is equivalent): lay
            # state out on the device mesh before the first step compiles
            self.use_mesh(mesh)
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        from ..distributed import bootstrap
        if not bootstrap.is_coordinator():
            # one progress bar per fleet, not one per process — every
            # host still runs the full loop (SPMD), only logging is
            # coordinator-scoped
            verbose = 0
        cbs = [ProgBarLogger(log_freq, verbose=verbose)]
        if self._optimizer is not None and \
                self._optimizer._lr_scheduler is not None:
            cbs.append(LRScheduler())
        if save_dir:
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        cbs += list(callbacks or [])
        cb_list = CallbackList(cbs)
        cb_list.set_model(self)
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cb_list.set_params({"epochs": epochs, "steps": steps,
                            "verbose": verbose})

        self.stop_training = False
        cb_list.on_train_begin()
        history = {"loss": []}
        # step telemetry (steps/sec, tokens/sec, data- vs device-wait,
        # loss) — only when a sink armed the registry; otherwise fit()
        # keeps its bare enumerate and pays nothing
        timer = observability.StepTimer() if observability.enabled() \
            else None
        for epoch in range(epochs):
            cb_list.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            epoch_logs = {}
            batches = enumerate(train_loader) if timer is None \
                else timer.timed_enumerate(train_loader)
            for step, batch in batches:
                if num_iters is not None and step >= num_iters:
                    break
                cb_list.on_train_batch_begin(step)
                if self._skip_batch:
                    # a resume-capable callback (resilience.
                    # ResilienceCallback) fast-forwards batches already
                    # baked into restored weights: consume from the
                    # stream, don't execute
                    self._skip_batch = False
                    continue
                inputs, labels = self._split_batch(batch)
                if xray_on_start:
                    # one abstract trace on the FIRST real batch's
                    # shapes: static FLOPs/bytes/peak-HBM land in
                    # self.xray_report + the observability gauges, and
                    # ERROR hazards (f64, host callbacks, H110 budget)
                    # abort before any step executes
                    xray_on_start = False
                    report = self.xray(inputs, labels,
                                       hbm_budget_bytes=hbm_budget_bytes)
                    errs = report.errors()
                    if errs:
                        raise RuntimeError(
                            "train-step X-ray found ERROR hazards:\n  "
                            + "\n  ".join(str(d) for d in errs))
                if shardplan is not None:
                    # same first-batch contract as xray_on_start: one
                    # abstract trace, report + gauges, abort on ERROR
                    # (S205 resharding, S207 collective-bound, H110
                    # per-chip budget) before a single step runs
                    req, shardplan = shardplan, None
                    from ..analysis import PlanRequest
                    if req is True:
                        req = PlanRequest()
                    plan = self.shardplan(inputs, labels, request=req)
                    errs = plan.errors()
                    if errs and getattr(req, "raise_on_error", True):
                        raise RuntimeError(
                            "train-step shard plan found ERRORs:\n  "
                            + "\n  ".join(str(d) for d in errs))
                loss = self.train_batch(inputs, labels)
                if timer is not None:
                    timer.step(loss=loss, inputs=inputs)
                logs = {"loss": loss}
                for m in self._metrics:
                    names = m.name()
                    vals = m.accumulate()
                    if isinstance(names, list):
                        logs.update(dict(zip(names, vals)))
                    else:
                        logs[names] = vals
                epoch_logs = logs
                cb_list.on_train_batch_end(step, logs)
                if self.stop_training:
                    break
            history["loss"].append(epoch_logs.get("loss"))
            cb_list.on_epoch_end(epoch, epoch_logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=verbose,
                                          _callbacks=cb_list)
            if self.stop_training:
                break
        cb_list.on_train_end()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None,
                 _callbacks=None):
        if isinstance(eval_data, Dataset):
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = eval_data
        cb_list = _callbacks or CallbackList(list(callbacks or []))
        if _callbacks is None:
            cb_list.set_model(self)
        for m in self._metrics:
            m.reset()
        cb_list.on_eval_begin()
        losses = []
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            inputs, labels = self._split_batch(batch)
            losses.append(self.eval_batch(inputs, labels))
        logs = {"loss": float(np.mean(losses)) if losses else None}
        for m in self._metrics:
            names = m.name()
            vals = m.accumulate()
            if isinstance(names, list):
                logs.update(dict(zip(names, vals)))
            else:
                logs[names] = vals
        cb_list.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = test_data
        outputs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch)
            out = self.predict_batch(inputs)
            outputs.append(out.numpy() if isinstance(out, Tensor)
                           else [o.numpy() for o in out])
        if stack_outputs and outputs and isinstance(outputs[0], np.ndarray):
            return [np.concatenate(outputs)]
        return [outputs]

    # ------------------------------------------------------------- persist
    def save(self, path, training=True):
        from ..framework.io import save as fsave

        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        from ..framework.io import load as fload

        self.network.set_state_dict(fload(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(fload(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary_fn(self.network, input_size, dtype)


def summary_fn(net, input_size=None, dtype=None):
    """paddle.summary analog: parameter table."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if getattr(p, "trainable", True):
            trainable += n
        rows.append((name, list(p.shape), n))
    width = max((len(r[0]) for r in rows), default=10) + 2
    lines = [f"{'Param':<{width}}{'Shape':<20}{'Count':>12}"]
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(shape):<20}{n:>12,}")
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


summary = summary_fn
