"""FLOPs counting for dygraph models (reference:
python/paddle/hapi/dynamic_flops.py `flops`/`dynamic_flops`).

Registers forward-post hooks on leaf layers, runs one forward pass on
zero inputs, and sums per-layer multiply-add counts.  Layer types without
a rule contribute 0 (matching the reference's warning-and-skip policy).
"""
from __future__ import annotations

import numpy as np

from .. import nn
from ..core.tensor import Tensor, to_tensor


def _prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


def _count_conv(layer, inputs, output):
    # kernel multiply-adds per output element x output elements (+ bias)
    w = layer.weight
    kernel_ops = _prod(w.shape[1:])  # in_ch/groups * kh * kw
    bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
    out_elems = _prod(output.shape)
    return out_elems * (kernel_ops + bias_ops)


def _count_linear(layer, inputs, output):
    in_features = layer.weight.shape[0]
    bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
    return _prod(output.shape) * (in_features + bias_ops)


def _count_norm(layer, inputs, output):
    return 2 * _prod(inputs[0].shape)


def _count_act(layer, inputs, output):
    return _prod(output.shape)


def _count_pool(layer, inputs, output):
    return _prod(output.shape)


def _count_embedding(layer, inputs, output):
    return 0


_RULES = {}


def register_hook_rule(layer_cls, fn):
    """Extension point matching the reference's custom_ops= argument."""
    _RULES[layer_cls] = fn


for _cls_name, _fn in [
    ("Conv1D", _count_conv), ("Conv2D", _count_conv), ("Conv3D", _count_conv),
    ("Conv1DTranspose", _count_conv), ("Conv2DTranspose", _count_conv),
    ("Linear", _count_linear),
    ("BatchNorm", _count_norm), ("BatchNorm1D", _count_norm),
    ("BatchNorm2D", _count_norm), ("BatchNorm3D", _count_norm),
    ("LayerNorm", _count_norm), ("GroupNorm", _count_norm),
    ("InstanceNorm2D", _count_norm), ("SyncBatchNorm", _count_norm),
    ("ReLU", _count_act), ("ReLU6", _count_act), ("GELU", _count_act),
    ("Sigmoid", _count_act), ("Softmax", _count_act), ("Silu", _count_act),
    ("Hardswish", _count_act), ("Hardsigmoid", _count_act),
    ("LeakyReLU", _count_act), ("Tanh", _count_act), ("PReLU", _count_act),
    ("AvgPool1D", _count_pool), ("AvgPool2D", _count_pool),
    ("AvgPool3D", _count_pool), ("MaxPool1D", _count_pool),
    ("MaxPool2D", _count_pool), ("MaxPool3D", _count_pool),
    ("AdaptiveAvgPool1D", _count_pool), ("AdaptiveAvgPool2D", _count_pool),
    ("AdaptiveAvgPool3D", _count_pool), ("AdaptiveMaxPool2D", _count_pool),
    ("Embedding", _count_embedding),
]:
    _cls = getattr(nn, _cls_name, None)
    if _cls is not None:
        _RULES[_cls] = _fn


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Total multiply-add count of one forward pass.

    ``input_size``: shape of a single zero input, e.g. [1, 3, 224, 224].
    ``custom_ops``: {LayerClass: fn(layer, inputs, output) -> int}.
    Returns the FLOPs as an int (reference returns the same and prints a
    per-layer table with print_detail=True).
    """
    rules = dict(_RULES)
    if custom_ops:
        rules.update(custom_ops)
    counts = []
    handles = []

    def make_hook(rule, layer):
        def hook(lyr, inputs, output):
            out = output[0] if isinstance(output, (tuple, list)) else output
            counts.append((type(lyr).__name__, int(rule(lyr, inputs, out))))
        return hook

    for sub in net.sublayers(include_self=True):
        if len(list(sub.children())) > 0:
            continue  # leaves only
        rule = rules.get(type(sub))
        if rule is None:
            for klass, fn in rules.items():
                if isinstance(sub, klass):
                    rule = fn
                    break
        if rule is not None:
            handles.append(sub.register_forward_post_hook(make_hook(rule, sub)))
    training = net.training
    net.eval()
    try:
        x = to_tensor(np.zeros(input_size, dtype=np.float32))
        net(x)
    finally:
        for h in handles:
            h.remove()
        if training:
            net.train()
    total = sum(c for _, c in counts)
    if print_detail:
        for name, c in counts:
            print(f"{name:>24}: {c:,}")
        print(f"Total FLOPs: {total:,}")
    return total
