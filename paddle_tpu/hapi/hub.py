"""paddle.hub (reference: python/paddle/hapi/hub.py — load models from
github/gitee repos implementing hubconf.py).  No network egress here:
`source='local'` works against a directory containing hubconf.py; remote
sources raise with staging instructions."""
from __future__ import annotations

import importlib.util
import os
import sys


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _entrypoints(mod):
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    if source != "local":
        raise RuntimeError(
            "no network egress: clone the repo locally and pass "
            "source='local'")
    return _entrypoints(_load_hubconf(repo_dir))


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    if source != "local":
        raise RuntimeError("no network egress: use source='local'")
    return getattr(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    if source != "local":
        raise RuntimeError("no network egress: use source='local'")
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(
            f"{model!r} not in hubconf entrypoints {_entrypoints(mod)}")
    return fn(**kwargs)
