"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import os

import numpy as np
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for cb in self.callbacks:
                    getattr(cb, name)(*args, **kwargs)
            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in logs.items())
            print(f"step {step}/{self.steps or '?'} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dur = time.time() - self._start
            print(f"Epoch {epoch + 1} done in {dur:.1f}s - "
                  + " - ".join(f"{k}: {v}" for k, v in (logs or {}).items()))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0

    def _improved(self, current):
        if self.best is None:
            return True
        if self.mode == "min":
            return current < self.best - self.min_delta
        return current > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            return
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self._improved(current):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return opt._lr_scheduler if opt is not None else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and self._sched() is not None:
            self._sched().step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch and self._sched() is not None:
            self._sched().step()


class VisualDL(Callback):
    """Scalar logging callback (reference: python/paddle/hapi/callbacks.py
    VisualDL writes via the visualdl LogWriter).  Zero-dep fallback: one
    JSONL file per run under log_dir, same scalar stream (loss/metrics per
    step, eval metrics per epoch); uses visualdl when importable."""

    def __init__(self, log_dir="./log"):
        self.log_dir = log_dir
        self._writer = None
        self._file = None
        self._step = 0

    def _ensure_writer(self):
        if self._writer is None and self._file is None:
            try:
                from visualdl import LogWriter  # optional

                self._writer = LogWriter(logdir=self.log_dir)
            except Exception:
                import os
                import time

                os.makedirs(self.log_dir, exist_ok=True)
                self._file = open(
                    os.path.join(self.log_dir,
                                 f"scalars_{int(time.time())}.jsonl"), "a")

    def _scalar(self, tag, value, step):
        import json

        self._ensure_writer()
        if self._writer is not None:
            self._writer.add_scalar(tag=tag, value=float(value), step=step)
        else:
            self._file.write(json.dumps(
                {"tag": tag, "value": float(value), "step": step}) + "\n")
            self._file.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            try:
                self._scalar(f"train/{k}", np.mean(v), self._step)
            except (TypeError, ValueError):
                pass

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            try:
                self._scalar(f"eval/{k}", np.mean(v), self._step)
            except (TypeError, ValueError):
                pass

    def on_train_end(self, logs=None):
        if self._file is not None:
            self._file.close()
            self._file = None
        if self._writer is not None:
            self._writer.close()
            self._writer = None
