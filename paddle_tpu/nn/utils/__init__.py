# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.nn.utils (reference: python/paddle/nn/utils/): weight_norm,
spectral_norm, parameters_to_vector, vector_to_parameters."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply
from ...core.tensor import Parameter, Tensor
from ..layer.layers import Layer

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(v, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v)))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0):
    """Reparameterize weight = g * v / ||v|| (reference: utils/weight_norm.py).

    Installs a forward_pre_hook recomputing the weight each call so both g
    and v train.
    """
    w = getattr(layer, name)
    w_val = w._value
    g0 = _norm_except(w_val, dim)
    v = Parameter(w_val)
    g = Parameter(g0.reshape(g0.shape))
    layer.add_parameter(name + "_v", v)
    layer.add_parameter(name + "_g", g)
    # demote original weight to a plain (recomputed) attribute
    layer._parameters.pop(name, None)

    def compute(layer_, inputs):
        def _wn(v_, g_):
            return g_ * v_ / jnp.maximum(_norm_except(v_, dim), 1e-12)
        new_w = apply("weight_norm", _wn, v, g)
        object.__setattr__(layer_, name, new_w)
        return None

    handle = layer.register_forward_pre_hook(compute)
    layer._weight_norm_handle = handle
    compute(layer, None)
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight"):
    handle = getattr(layer, "_weight_norm_handle", None)
    if handle is not None:
        handle.remove()
    v = layer._parameters.pop(name + "_v")
    g = layer._parameters.pop(name + "_g")
    w = apply("weight_norm_final",
              lambda v_, g_: g_ * v_ / jnp.maximum(
                  _norm_except(v_, 0), 1e-12), v, g)
    layer.add_parameter(name, Parameter(w._value))
    return layer


def spectral_norm(layer: Layer, name: str = "weight", n_power_iterations: int
                  = 1, eps: float = 1e-12, dim: int = 0):
    """Power-iteration spectral normalization as a forward_pre_hook."""
    w = getattr(layer, name)
    h = w.shape[dim]
    rest = int(np.prod(w.shape)) // h
    from ...ops import random as rnd

    u = jax.random.normal(rnd.next_key(), (h,), jnp.float32)
    state = {"u": u / jnp.linalg.norm(u)}
    v_param = Parameter(w._value)
    layer.add_parameter(name + "_orig", v_param)
    layer._parameters.pop(name, None)

    def compute(layer_, inputs):
        def _sn(w_):
            w_mat = jnp.moveaxis(w_, dim, 0).reshape(h, rest)
            u_ = state["u"]
            for _ in range(n_power_iterations):
                v_ = w_mat.T @ u_
                v_ = v_ / jnp.maximum(jnp.linalg.norm(v_), eps)
                u_ = w_mat @ v_
                u_ = u_ / jnp.maximum(jnp.linalg.norm(u_), eps)
            sigma = u_ @ w_mat @ v_
            if not isinstance(u_, jax.core.Tracer):
                state["u"] = jax.lax.stop_gradient(u_)
            return w_ / sigma
        new_w = apply("spectral_norm", _sn, v_param)
        object.__setattr__(layer_, name, new_w)
        return None

    layer.register_forward_pre_hook(compute)
    compute(layer, None)
    return layer


def parameters_to_vector(parameters, name=None):
    from ...ops.manipulation import concat, reshape

    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec: Tensor, parameters, name=None):
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        chunk = vec[offset:offset + n]
        p._value = chunk._value.reshape(tuple(p.shape))
        offset += n
    return parameters
