# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Activation functionals (reference: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def relu(x, name=None):
    return apply("relu", jax.nn.relu, _t(x))


def relu_(x, name=None):
    return x._rebind(relu(x))


def relu6(x, name=None):
    return apply("relu6", jax.nn.relu6, _t(x))


def _gelu_impl(v, approximate=False):
    return jax.nn.gelu(v, approximate=approximate)


def gelu(x, approximate=False, name=None):
    # distinct op types so graph passes can tell the variants apart
    # (fuse_linear_act only fuses the exact-erf form)
    op = "gelu_tanh" if approximate else "gelu"
    return apply(op, _gelu_impl, _t(x), approximate=approximate)


def sigmoid(x, name=None):
    return apply("sigmoid", jax.nn.sigmoid, _t(x))


def log_sigmoid(x, name=None):
    return apply("log_sigmoid", jax.nn.log_sigmoid, _t(x))


def tanh(x, name=None):
    return apply("tanh", jnp.tanh, _t(x))


def _softmax_impl(v, axis=-1, dtype=None):
    if dtype is not None:
        v = v.astype(dtype)
    return jax.nn.softmax(v, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import to_np

    return apply("softmax", _softmax_impl, _t(x), axis=axis,
                 dtype=to_np(dtype) if dtype is not None else None)


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._rebind(softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    def _lsm(v):
        if dtype is not None:
            from ...core.dtype import to_np

            v = v.astype(to_np(dtype))
        return jax.nn.log_softmax(v, axis=axis)
    return apply("log_softmax", _lsm, _t(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu", lambda v: jax.nn.leaky_relu(v, negative_slope), _t(x))


def elu(x, alpha=1.0, name=None):
    return apply("elu", lambda v: jax.nn.elu(v, alpha), _t(x))


def elu_(x, alpha=1.0, name=None):
    return x._rebind(elu(x, alpha))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply("selu",
                 lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), _t(x))


def celu(x, alpha=1.0, name=None):
    return apply("celu", lambda v: jax.nn.celu(v, alpha), _t(x))


def prelu(x, weight, data_format="NCHW", name=None):
    def _prelu(v, w):
        if w.size == 1:
            alpha = w.reshape(())
        else:
            shape = [1] * v.ndim
            ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
            shape[ch_axis] = w.size
            alpha = w.reshape(shape)
        return jnp.where(v > 0, v, alpha * v)
    return apply("prelu", _prelu, _t(x), _t(weight))


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    from ...ops import random as rnd

    if training:
        key = rnd.next_key()

        def _rrelu(v):
            alpha = jax.random.uniform(key, v.shape, v.dtype, lower, upper)
            return jnp.where(v >= 0, v, alpha * v)
        return apply("rrelu", _rrelu, _t(x))
    mid = (lower + upper) / 2.0
    return apply("rrelu", lambda v: jnp.where(v >= 0, v, mid * v), _t(x))


def hardshrink(x, threshold=0.5, name=None):
    return apply("hardshrink",
                 lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), _t(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply("hardtanh", lambda v: jnp.clip(v, min, max), _t(x))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply("hardsigmoid",
                 lambda v: jnp.clip(v * slope + offset, 0.0, 1.0), _t(x))


def hardswish(x, name=None):
    return apply("hardswish",
                 lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, _t(x))


def swish(x, name=None):
    return apply("swish", jax.nn.silu, _t(x))


def silu(x, name=None):
    return apply("silu", jax.nn.silu, _t(x))


def mish(x, name=None):
    return apply("mish", lambda v: v * jnp.tanh(jax.nn.softplus(v)), _t(x))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        "softplus",
        lambda v: jnp.where(v * beta > threshold, v,
                            jax.nn.softplus(v * beta) / beta), _t(x))


def softshrink(x, threshold=0.5, name=None):
    return apply(
        "softshrink",
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0.0)), _t(x))


def softsign(x, name=None):
    return apply("softsign", jax.nn.soft_sign, _t(x))


def tanhshrink(x, name=None):
    return apply("tanhshrink", lambda v: v - jnp.tanh(v), _t(x))


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply("thresholded_relu",
                 lambda v: jnp.where(v > threshold, v, value), _t(x))


def glu(x, axis=-1, name=None):
    return apply("glu", lambda v: jax.nn.glu(v, axis=axis), _t(x))


def maxout(x, groups, axis=1, name=None):
    def _maxout(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return apply("maxout", _maxout, _t(x))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...ops import random as rnd

    key = rnd.next_key()

    def _gumbel(v):
        g = jax.random.gumbel(key, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            # straight-through: hard value forward, soft gradient backward
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y
    return apply("gumbel_softmax", _gumbel, _t(x))


# --------------------------------------------------------------------------
# Analytic eager-VJP rules (core/dispatch.py register_eager_vjp): softmax
# and both gelu variants have closed-form backwards; jax.vjp otherwise
# re-linearizes on every eager call (VERDICT r3 #2).
def _softmax_rule(vals, attrs):
    if attrs.get("dtype") is not None:
        return None
    (a,) = vals
    axis = attrs.get("axis", -1)
    out = jax.nn.softmax(a, axis=axis)

    def vjp(ct):
        inner = jnp.sum(ct * out, axis=axis, keepdims=True)
        return (((ct - inner) * out).astype(a.dtype),)
    return out, vjp


_SQRT_2_OVER_PI = 0.7978845608028654
_GELU_C = 0.044715


def _gelu_exact_rule(vals, attrs):
    if attrs.get("approximate"):
        return None
    (a,) = vals
    out = jax.nn.gelu(a, approximate=False)

    def vjp(ct):
        cdf = 0.5 * (1.0 + jax.scipy.special.erf(a * 0.7071067811865476))
        pdf = jnp.exp(-0.5 * a * a) * 0.3989422804014327  # 1/sqrt(2*pi)
        return ((ct * (cdf + a * pdf)).astype(a.dtype),)
    return out, vjp


def _gelu_tanh_rule(vals, attrs):
    if not attrs.get("approximate"):
        return None
    (a,) = vals
    out = jax.nn.gelu(a, approximate=True)

    def vjp(ct):
        u = _SQRT_2_OVER_PI * (a + _GELU_C * a * a * a)
        t = jnp.tanh(u)
        du = _SQRT_2_OVER_PI * (1.0 + 3.0 * _GELU_C * a * a)
        g = 0.5 * (1.0 + t) + 0.5 * a * (1.0 - t * t) * du
        return ((ct * g).astype(a.dtype),)
    return out, vjp


def _register_activation_rules():
    from ...core.dispatch import register_eager_vjp

    register_eager_vjp("softmax", _softmax_impl, _softmax_rule)
    register_eager_vjp("gelu", _gelu_impl, _gelu_exact_rule)
    register_eager_vjp("gelu_tanh", _gelu_impl, _gelu_tanh_rule)


_register_activation_rules()
