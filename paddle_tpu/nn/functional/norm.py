# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

batch_norm running-stat updates are expressed as in-place buffer rebinds; the
to_static tracer captures them as extra program outputs so compiled training
steps update state functionally (the XLA-idiomatic version of the reference's
mutable inference/variance variables).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply, is_grad_enabled
from ...core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    channel_axis = 1 if data_format[1] == "C" else -1
    use_batch_stats = training and not use_global_stats

    def _bn(v, rm, rv, *wb):
        axes = tuple(i for i in range(v.ndim) if i != channel_axis % v.ndim)
        if use_batch_stats:
            mean = jnp.mean(v, axis=axes)
            var = jnp.var(v, axis=axes)
        else:
            mean, var = rm, rv
        shape = [1] * v.ndim
        shape[channel_axis] = v.shape[channel_axis]
        out = (v - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + epsilon)
        if wb:
            w, b = wb
            out = out * w.reshape(shape) + b.reshape(shape)
        return out.astype(v.dtype)

    args = [_t(x), _t(running_mean), _t(running_var)]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    out = apply("batch_norm", _bn, *args)

    if use_batch_stats and isinstance(running_mean, Tensor):
        # update running stats (paddle: stat = momentum*stat + (1-m)*batch)
        with_no_grad_update(x, running_mean, running_var, channel_axis, momentum)
    return out


def with_no_grad_update(x, running_mean, running_var, channel_axis, momentum):
    from ...core.dispatch import no_grad_ctx

    from ...static import graph as G

    if isinstance(x, G.Variable):
        # static mode: running-stat update becomes a writeback op
        def _upd(v, rm, rv):
            axes = tuple(i for i in range(v.ndim)
                         if i != channel_axis % v.ndim)
            mean = jnp.mean(v.astype(jnp.float32), axis=axes)
            var = jnp.var(v.astype(jnp.float32), axis=axes)
            return (momentum * rm + (1.0 - momentum) * mean.astype(rm.dtype),
                    momentum * rv + (1.0 - momentum) * var.astype(rv.dtype))

        G.record_writeback_op("bn_stats", _upd,
                              [x, running_mean, running_var],
                              [running_mean, running_var])
        return

    with no_grad_ctx():
        v = x._value
        axes = tuple(i for i in range(v.ndim) if i != channel_axis % v.ndim)
        mean = jnp.mean(v.astype(jnp.float32), axis=axes)
        var = jnp.var(v.astype(jnp.float32), axis=axes)
        running_mean._value = (momentum * running_mean._value
                               + (1.0 - momentum) * mean.astype(
                                   running_mean._value.dtype))
        running_var._value = (momentum * running_var._value
                              + (1.0 - momentum) * var.astype(
                                  running_var._value.dtype))


def _layer_norm_impl(v, *wb, normalized_shape=(), epsilon=1e-5):
    axes = tuple(range(v.ndim - len(normalized_shape), v.ndim))
    mean = jnp.mean(v, axis=axes, keepdims=True)
    var = jnp.var(v, axis=axes, keepdims=True)
    out = (v - mean) * jax.lax.rsqrt(var + epsilon)
    if wb:
        out = out * wb[0].reshape(tuple(normalized_shape))
        if len(wb) > 1:
            out = out + wb[1].reshape(tuple(normalized_shape))
    return out.astype(v.dtype)


def _layer_norm_rule(vals, attrs):
    ns = tuple(attrs.get("normalized_shape") or ())
    eps = attrs.get("epsilon", 1e-5)
    v, wb = vals[0], vals[1:]
    nd = len(ns)
    if nd == 0 or v.ndim < nd:
        return None
    axes = tuple(range(v.ndim - nd, v.ndim))
    lead = tuple(range(v.ndim - nd))
    mean = jnp.mean(v, axis=axes, keepdims=True)
    var = jnp.var(v, axis=axes, keepdims=True)
    ivar = jax.lax.rsqrt(var + eps)
    xhat = (v - mean) * ivar
    w = wb[0].reshape(ns) if wb else None
    out = xhat if w is None else xhat * w
    if len(wb) > 1:
        out = out + wb[1].reshape(ns)
    out = out.astype(v.dtype)

    def vjp(ct):
        # classic LN backward: gx = ivar*(gxh - E[gxh] - xhat*E[gxh*xhat])
        gxh = ct if w is None else ct * w
        m1 = jnp.mean(gxh, axis=axes, keepdims=True)
        m2 = jnp.mean(gxh * xhat, axis=axes, keepdims=True)
        grads = [(ivar * (gxh - m1 - xhat * m2)).astype(v.dtype)]
        if wb:
            gw = jnp.sum(ct * xhat, axis=lead) if lead else ct * xhat
            grads.append(gw.reshape(wb[0].shape).astype(wb[0].dtype))
            if len(wb) > 1:
                gb = jnp.sum(ct, axis=lead) if lead else ct
                grads.append(gb.reshape(wb[1].shape).astype(wb[1].dtype))
        return tuple(grads)
    return out, vjp


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
        if bias is not None:
            args.append(_t(bias))
    return apply("layer_norm", _layer_norm_impl, *args,
                 normalized_shape=tuple(normalized_shape), epsilon=epsilon)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def _in(v, *wb):
        axes = tuple(range(2, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + eps)
        if wb:
            shape = (1, v.shape[1]) + (1,) * (v.ndim - 2)
            out = out * wb[0].reshape(shape)
            if len(wb) > 1:
                out = out + wb[1].reshape(shape)
        return out.astype(v.dtype)

    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
        if bias is not None:
            args.append(_t(bias))
    return apply("instance_norm", _in, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    def _gn(v, *wb):
        if data_format[-1] == "C":
            v = jnp.moveaxis(v, -1, 1)
        n, c = v.shape[:2]
        spatial = v.shape[2:]
        g = v.reshape((n, num_groups, c // num_groups) + spatial)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v.shape)
        if wb:
            shape = (1, c) + (1,) * len(spatial)
            out = out * wb[0].reshape(shape)
            if len(wb) > 1:
                out = out + wb[1].reshape(shape)
        if data_format[-1] == "C":
            out = jnp.moveaxis(out, 1, -1)
        return out.astype(v.dtype)

    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
        if bias is not None:
            args.append(_t(bias))
    return apply("group_norm", _gn, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def _lrn(v):
        ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
        sq = jnp.square(v)
        half = size // 2
        pads = [(0, 0)] * v.ndim
        pads[ch_axis] = (half, size - half - 1)
        sq = jnp.pad(sq, pads)
        window = [1] * v.ndim
        window[ch_axis] = size
        summed = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add, tuple(window), (1,) * v.ndim, "VALID")
        # the reference IMPLEMENTS avg_pool over the zero-padded window
        # (norm.py:547 — divisor always `size`, edges included), i.e.
        # k + alpha*sum/size, like torch; its docstring's alpha*sum is
        # not what it computes (verified element-exact vs torch oracle)
        return v / jnp.power(k + alpha * summed / size, beta)
    return apply("local_response_norm", _lrn, _t(x))


def spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12, name=None):
    def _sn(w, u_, v_):
        w_mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        for _ in range(power_iters):
            v_ = w_mat.T @ u_
            v_ = v_ / (jnp.linalg.norm(v_) + eps)
            u_ = w_mat @ v_
            u_ = u_ / (jnp.linalg.norm(u_) + eps)
        sigma = u_ @ w_mat @ v_
        return w / sigma
    return apply("spectral_norm", _sn, _t(weight), _t(u), _t(v))


def _register_norm_rules():
    from ...core.dispatch import register_eager_vjp

    register_eager_vjp("layer_norm", _layer_norm_impl, _layer_norm_rule)


_register_norm_rules()
