# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True,
                  label_smoothing=0.0, name=None):
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply("cross_entropy", _cross_entropy_impl, *args,
                 ignore_index=ignore_index, reduction=reduction,
                 soft_label=soft_label, axis=axis, use_softmax=use_softmax,
                 label_smoothing=label_smoothing)


def _cross_entropy_impl(logits, lab, *maybe_w, ignore_index=-100,
                        reduction="mean", soft_label=False, axis=-1,
                        use_softmax=True, label_smoothing=0.0):
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.clip(logits, 1e-15, 1.0))
    n_classes = logits.shape[axis]
    if soft_label:
        soft = lab
        if label_smoothing > 0.0:
            soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
        loss = -jnp.sum(soft * logp, axis=axis)
        valid = None
    else:
        loss, valid, safe = _hard_label_nll(logp, lab, ignore_index,
                                            axis=axis)
        if label_smoothing > 0.0:
            smooth_loss = -jnp.mean(logp, axis=axis)
            loss = (1 - label_smoothing) * loss + \
                label_smoothing * jnp.where(valid, smooth_loss, 0.0)
        if maybe_w:
            w = maybe_w[0][safe]
            loss = loss * jnp.where(valid, w, 0.0)
    if reduction == "mean":
        if valid is not None:
            if maybe_w:
                denom = jnp.sum(jnp.where(valid, maybe_w[0][safe], 0.0))
            else:
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return jnp.mean(loss)
    return _reduce(loss, reduction)


def _hard_label_nll(logp, lab, ignore_index, axis=-1):
    """Shared hard-label NLL pieces: (loss, valid, safe).  Used by BOTH
    _cross_entropy_impl's hard-label branch and the analytic rule so the
    two can never silently diverge numerically."""
    lab_idx = lab
    if lab_idx.ndim == logp.ndim:
        lab_idx = jnp.squeeze(lab_idx, axis)
    lab_idx = lab_idx.astype(jnp.int32)
    valid = lab_idx != ignore_index
    safe = jnp.where(valid, lab_idx, 0)
    picked = jnp.squeeze(jnp.take_along_axis(
        logp, jnp.expand_dims(safe, axis), axis=axis), axis)
    return jnp.where(valid, -picked, 0.0), valid, safe


def _cross_entropy_rule(vals, attrs):
    """Analytic softmax-CE backward — g = softmax, minus 1 at the label
    positions — for the hard-label/no-weight/no-smoothing hot case
    (every classification training loop's loss; reference codegen
    analog: softmax_with_cross_entropy_grad)."""
    if len(vals) != 2 or attrs.get("soft_label") \
            or not attrs.get("use_softmax", True) \
            or attrs.get("label_smoothing", 0.0):
        return None
    logits, lab = vals
    axis = attrs.get("axis", -1)
    if axis not in (-1, logits.ndim - 1):
        return None
    if not jnp.issubdtype(lab.dtype, jnp.integer):
        return None
    red = attrs.get("reduction", "mean")
    if red not in ("mean", "sum", "none"):
        return None
    logp = jax.nn.log_softmax(logits, axis=-1)
    loss, valid, safe = _hard_label_nll(logp, lab,
                                        attrs.get("ignore_index", -100))
    denom = None
    if red == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        out = jnp.sum(loss) / denom
    elif red == "sum":
        out = jnp.sum(loss)
    else:
        out = loss

    def vjp(ct):
        # softmax minus scatter of 1 at label positions — no dense
        # one-hot temp (for an lm-head the one-hot would double the
        # backward's peak memory)
        g = jnp.exp(logp)
        idx = jnp.expand_dims(safe, -1)
        upd = jnp.take_along_axis(g, idx, axis=-1) - 1.0
        g = jnp.put_along_axis(g, idx, upd, axis=-1, inplace=False)
        g = g * valid[..., None].astype(g.dtype)
        if red == "mean":
            g = g * (ct / denom)
        elif red == "sum":
            g = g * ct
        else:
            g = g * ct[..., None]
        return (g.astype(logits.dtype), None)  # int labels: no grad

    return out, vjp


def _register_loss_rules():
    from ...core.dispatch import register_eager_vjp

    register_eager_vjp("cross_entropy", _cross_entropy_impl,
                       _cross_entropy_rule)


_register_loss_rules()


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax

    loss = loss.unsqueeze(axis) if not soft_label else loss
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def _nll(logp, lab, *maybe_w):
        lab = lab.astype(jnp.int32)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0] \
            if logp.ndim == 2 else jnp.take_along_axis(
                logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = -picked
        wsum = None
        if maybe_w:
            w = maybe_w[0][safe]
            loss = loss * w
            wsum = jnp.sum(jnp.where(valid, w, 0.0))
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = wsum if wsum is not None else jnp.maximum(
                jnp.sum(valid.astype(loss.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply("nll_loss", _nll, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply("mse_loss",
                 lambda a, b: _reduce(jnp.square(a - b), reduction),
                 _t(input), _t(label))


def l1_loss(input, label, reduction="mean", name=None):
    return apply("l1_loss",
                 lambda a, b: _reduce(jnp.abs(a - b), reduction),
                 _t(input), _t(label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def _sl1(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle multiplies by delta
        loss = loss * delta
        return _reduce(loss, reduction)
    return apply("smooth_l1_loss", _sl1, _t(input), _t(label))


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def _huber(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply("huber_loss", _huber, _t(input), _t(label))


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def _bce(p, lab, *maybe_w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(lab * jnp.log(p) + (1 - lab) * jnp.log(1 - p))
        if maybe_w:
            loss = loss * maybe_w[0]
        return _reduce(loss, reduction)
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply("binary_cross_entropy", _bce, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def _bcewl(z, lab, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]; i += 1
        if pos_weight is not None:
            pw = extra[i]; i += 1
        # stable: max(z,0) - z*y + log(1+exp(-|z|))
        base = jnp.maximum(z, 0) - z * lab + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            log_weight = (pw - 1.0) * lab + 1.0
            base = ((1 - lab) * z + log_weight *
                    (jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(-z, 0)))
        if w is not None:
            base = base * w
        return _reduce(base, reduction)
    args = [_t(logit), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    if pos_weight is not None:
        args.append(_t(pos_weight))
    return apply("bce_with_logits", _bcewl, *args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def _kl(logp, t):
        if log_target:
            loss = jnp.exp(t) * (t - logp)
        else:
            loss = t * (jnp.log(jnp.clip(t, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply("kl_div", _kl, _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def _mrl(a, b, lab):
        return _reduce(jnp.maximum(0.0, -lab * (a - b) + margin), reduction)
    return apply("margin_ranking_loss", _mrl, _t(input), _t(other), _t(label))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def _hel(a, lab):
        loss = jnp.where(lab == 1.0, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply("hinge_embedding_loss", _hel, _t(input), _t(label))


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def _cel(a, b, lab):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(lab == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply("cosine_embedding_loss", _cel, _t(input1), _t(input2), _t(label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def _tml(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return apply("triplet_margin_loss", _tml, _t(input), _t(positive),
                 _t(negative))


def square_error_cost(input, label):
    return apply("square_error_cost", lambda a, b: jnp.square(a - b),
                 _t(input), _t(label))


def log_loss(input, label, epsilon=1e-4, name=None):
    def _ll(p, lab):
        return -lab * jnp.log(p + epsilon) - (1 - lab) * jnp.log(1 - p + epsilon)
    return apply("log_loss", _ll, _t(input), _t(label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def _sfl(z, lab, *maybe_norm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * lab + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * lab + (1 - p) * (1 - lab)
        a_t = alpha * lab + (1 - alpha) * (1 - lab)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if maybe_norm:
            loss = loss / maybe_norm[0]
        return _reduce(loss, reduction)
    args = [_t(logit), _t(label)]
    if normalizer is not None:
        args.append(_t(normalizer))
    return apply("sigmoid_focal_loss", _sfl, *args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via optax's implementation (XLA-lowered dynamic program)."""
    import optax

    def _ctc(lp, lab, il, ll):
        # optax expects [B, T, C] logits and paddings
        logits = jnp.transpose(lp, (1, 0, 2)) if lp.ndim == 3 else lp
        B, T, C = logits.shape
        logit_paddings = (jnp.arange(T)[None, :] >= il[:, None]).astype(
            logits.dtype)
        Lmax = lab.shape[1]
        label_paddings = (jnp.arange(Lmax)[None, :] >= ll[:, None]).astype(
            logits.dtype)
        loss = optax.ctc_loss(logits, logit_paddings, lab.astype(jnp.int32),
                              label_paddings, blank_id=blank)
        if reduction == "mean":
            return jnp.mean(loss / ll.astype(loss.dtype))
        return _reduce(loss, reduction)
    return apply("ctc_loss", _ctc, _t(log_probs), _t(labels),
                 _t(input_lengths), _t(label_lengths))
