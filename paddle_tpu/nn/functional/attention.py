# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Attention functionals.

The reference implements fused attention as hand-written CUDA
(/root/reference/paddle/fluid/operators/fused/fused_attention_op.cu,
fmha_ref.h).  Here the TPU-native path is a Pallas flash-attention kernel
(paddle_tpu/kernels/flash_attention.py) on TPU, with an XLA-fused jnp
reference path everywhere else.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.flags import flag
from ...core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _sdpa_reference(q, k, v, mask, dropout_p, causal, scale):
    """[B, T, H, D] layout (paddle flash_attention layout)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        t, s = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((t, s), bool), k=s - t)
        logits = jnp.where(causal_mask, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """paddle.nn.functional.scaled_dot_product_attention: [B, T, H, D]."""
    def _sdpa(q, k, v, *maybe_mask):
        mask = maybe_mask[0] if maybe_mask else None
        if flag("use_pallas_kernels") and jax.default_backend() == "tpu" \
                and mask is None and dropout_p == 0.0:
            from ...kernels.flash_attention import flash_attention_bthd

            return flash_attention_bthd(q, k, v, causal=is_causal)
        return _sdpa_reference(q, k, v, mask, dropout_p, is_causal, None)
    args = [_t(query), _t(key), _t(value)]
    if attn_mask is not None:
        args.append(_t(attn_mask))
    return apply("scaled_dot_product_attention", _sdpa, *args)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal)
    if return_softmax:
        return out, None
    return out, None
