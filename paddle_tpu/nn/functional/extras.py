# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Long-tail functional ops (reference: python/paddle/nn/functional/
vision.py, loss.py, extension.py — affine_grid, temporal_shift,
max_unpool, dice/npair losses, hsigmoid, margin softmax, gather_tree,
sparse_attention).  All are XLA lowerings; the reference implements each
as a CUDA/CPU kernel pair.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply
from ...core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


# --------------------------------------------------------------------------
# vision
# --------------------------------------------------------------------------

def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Sampling grid from batched 2x3 affine matrices (reference:
    nn/functional/vision.py affine_grid -> affine_grid op)."""
    if isinstance(out_shape, Tensor):
        out_shape = [int(v) for v in np.asarray(out_shape.numpy())]
    N, _, H, W = [int(s) for s in out_shape]

    def _fn(th):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1
            ys = (jnp.arange(H) * 2 + 1) / H - 1
        gx, gy = jnp.meshgrid(xs, ys)  # [H, W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [H, W, 3]
        # [N, H, W, 2] = base @ theta^T per batch
        return jnp.einsum("hwk,nck->nhwc", base, th.astype(jnp.float32)
                          ).astype(th.dtype)

    return apply("affine_grid", _fn, _t(theta))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM temporal channel shift (reference: nn/functional/extension.py
    temporal_shift -> temporal_shift op): first `shift_ratio` of channels
    reads the NEXT segment, the second reads the PREVIOUS, rest copies."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"bad data_format {data_format}")

    def _fn(v):
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 3, 1, 2))
        NT, C, H, W = v.shape
        N = NT // seg_num
        r = v.reshape(N, seg_num, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        fwd = jnp.concatenate([r[:, 1:, :c1], jnp.zeros_like(r[:, :1, :c1])],
                              axis=1)
        bwd = jnp.concatenate([jnp.zeros_like(r[:, :1, c1:c2]),
                               r[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([fwd, bwd, r[:, :, c2:]], axis=2)
        out = out.reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply("temporal_shift", _fn, _t(x))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """[left, right, top, bottom] zero padding (reference: common.py
    zeropad2d)."""
    l, r, t, b = [int(p) for p in padding]

    def _fn(v):
        if data_format == "NCHW":
            cfg = ((0, 0), (0, 0), (t, b), (l, r))
        else:
            cfg = ((0, 0), (t, b), (l, r), (0, 0))
        return jnp.pad(v, cfg)

    return apply("zeropad2d", _fn, _t(x))


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched diagonal embedding (reference: tensor/creation.py
    diag_embed op)."""

    def _fn(v):
        n = v.shape[-1] + abs(int(offset))
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(v)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        # diagonal planes currently in the last two axes; move them
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        order = sorted([(d1, nd - 2), (d2, nd - 1)])
        for dst, src in order:
            perm.insert(dst, src)
        return jnp.transpose(out, perm)

    return apply("diag_embed", _fn, _t(input))


# --------------------------------------------------------------------------
# max_unpool
# --------------------------------------------------------------------------

def _unpool_out_size(in_sp, kernel, stride, padding, output_size, n):
    if output_size is not None:
        sp = [int(s) for s in output_size]
        return sp[-n:]
    return [(in_sp[i] - 1) * stride[i] - 2 * padding[i] + kernel[i]
            for i in range(n)]


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Inverse of max_pool2d(return_mask=True) (reference:
    nn/functional/pooling.py max_unpool2d -> unpool op).  `indices` are
    flat input-spatial positions as produced by our max_pool2d."""
    if data_format != "NCHW":
        raise NotImplementedError("max_unpool2d: NCHW only")
    k = _tuplize(kernel_size, 2)
    s = _tuplize(stride if stride is not None else kernel_size, 2)
    p = _tuplize(padding, 2)

    def _fn(v, idx):
        N, C, H, W = v.shape
        Ho, Wo = _unpool_out_size((H, W), k, s, p, output_size, 2)
        flat = jnp.zeros((N, C, Ho * Wo), v.dtype)
        vi = v.reshape(N, C, H * W)
        ii = idx.reshape(N, C, H * W)
        b = jnp.arange(N)[:, None, None]
        c = jnp.arange(C)[None, :, None]
        flat = flat.at[b, c, ii].set(vi)
        return flat.reshape(N, C, Ho, Wo)

    return apply("max_unpool2d", _fn, _t(x), _t(indices))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    if data_format != "NCL":
        raise NotImplementedError("max_unpool1d: NCL only")
    xx = _t(x)
    ii = _t(indices)
    from ...ops.manipulation import unsqueeze, squeeze

    k = _tuplize(kernel_size, 1)[0]
    s = _tuplize(stride if stride is not None else kernel_size, 1)[0]
    p = _tuplize(padding, 1)[0]
    osz = [1, int(output_size[-1])] if output_size is not None else None
    out = max_unpool2d(unsqueeze(xx, 2), unsqueeze(ii, 2), (1, k), (1, s),
                       (0, p), output_size=osz)
    return squeeze(out, 2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """Indices are flat D*H*W positions (matching the 2d convention)."""
    if data_format != "NCDHW":
        raise NotImplementedError("max_unpool3d: NCDHW only")
    k = _tuplize(kernel_size, 3)
    s = _tuplize(stride if stride is not None else kernel_size, 3)
    p = _tuplize(padding, 3)

    def _fn(v, idx):
        N, C, D, H, W = v.shape
        Do, Ho, Wo = _unpool_out_size((D, H, W), k, s, p, output_size, 3)
        flat = jnp.zeros((N, C, Do * Ho * Wo), v.dtype)
        vi = v.reshape(N, C, -1)
        ii = idx.reshape(N, C, -1)
        b = jnp.arange(N)[:, None, None]
        c = jnp.arange(C)[None, :, None]
        flat = flat.at[b, c, ii].set(vi)
        return flat.reshape(N, C, Do, Ho, Wo)

    return apply("max_unpool3d", _fn, _t(x), _t(indices))


# --------------------------------------------------------------------------
# losses
# --------------------------------------------------------------------------

def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - 2|A.B| / (|A|+|B|) over the last dim's class probs (reference:
    nn/functional/loss.py dice_loss)."""

    def _fn(x, y):
        y1 = jax.nn.one_hot(y.squeeze(-1), x.shape[-1], dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inter = jnp.sum(x * y1, axis=red)
        union = jnp.sum(x, axis=red) + jnp.sum(y1, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return apply("dice_loss", _fn, _t(input), _t(label))


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (Sohn 2016) (reference: nn/functional/loss.py
    npair_loss): cross-entropy over anchor-positive similarities + L2."""

    def _fn(a, p, y):
        a32 = a.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        reg = jnp.mean(jnp.sum(a32 * a32, -1)) + jnp.mean(
            jnp.sum(p32 * p32, -1))
        sim = a32 @ p32.T  # [B, B]
        ymat = (y[:, None] == y[None, :]).astype(jnp.float32)
        ymat = ymat / jnp.sum(ymat, -1, keepdims=True)
        ce = jnp.mean(jnp.sum(
            -ymat * jax.nn.log_softmax(sim, -1), axis=-1))
        return ce + l2_reg * reg * 0.25

    return apply("npair_loss", _fn, _t(anchor), _t(positive), _t(labels))


def _default_huffman_paths(num_classes):
    """Complete-binary-tree path tables (heap layout: internal nodes
    0..num_classes-2, leaf for class c at heap id num_classes-1+c).
    Returns (path_table, path_code) padded with -1, shape [C, D]."""
    depth = max(1, math.ceil(math.log2(max(2, num_classes))))
    table = -np.ones((num_classes, depth + 1), np.int64)
    code = -np.ones((num_classes, depth + 1), np.int64)
    for cls in range(num_classes):
        node = num_classes - 1 + cls  # heap id of leaf
        path = []
        while node != 0:
            parent = (node - 1) // 2
            path.append((parent, node == 2 * parent + 2))
            node = parent
        for i, (nid, bit) in enumerate(reversed(path)):
            table[cls, i] = nid
            code[cls, i] = int(bit)
    return table, code


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference: nn/functional/loss.py
    hsigmoid_loss -> hierarchical_sigmoid op).  Default tree = complete
    binary tree over classes; custom trees via path_table/path_code
    ([batch or C, D], -1-padded)."""
    if path_table is None:
        tbl, code = _default_huffman_paths(int(num_classes))
        tbl_t, code_t = to_tensor(tbl), to_tensor(code)
        per_class = True
    else:
        tbl_t, code_t = _t(path_table), _t(path_code)
        per_class = False

    args = [_t(input), _t(label), _t(weight), tbl_t, code_t]
    has_bias = bias is not None
    if has_bias:
        args.append(_t(bias))

    def _fn(x, y, w, tbl, code, *rest):
        b = rest[0] if rest else None
        if per_class:
            tpath = tbl[y]       # [B, D]
            tcode = code[y]
        else:
            tpath = tbl
            tcode = code
        mask = (tpath >= 0).astype(jnp.float32)
        safe = jnp.maximum(tpath, 0)
        wsel = w[safe]           # [B, D, F]
        logits = jnp.einsum("bf,bdf->bd", x.astype(jnp.float32),
                            wsel.astype(jnp.float32))
        if b is not None:
            logits = logits + b.reshape(-1)[safe]
        # code bit 1 -> right child -> sigmoid(logit); bit 0 -> 1-sigmoid
        sign = jnp.where(tcode > 0, 1.0, -1.0)
        logp = jax.nn.log_sigmoid(sign * logits) * mask
        return -jnp.sum(logp, axis=-1, keepdims=True)

    return apply("hsigmoid_loss", _fn, *args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace-family margin softmax (reference: nn/functional/loss.py
    margin_cross_entropy -> margin_cross_entropy op): target logit
    cos(m1*theta + m2) - m3, all scaled by s.  `group` accepts a
    model-parallel group for sharded classes; under GSPMD the sharded
    matmul + softmax compile to the same collectives, so only the math
    lives here."""

    def _fn(lg, y):
        lg32 = jnp.clip(lg.astype(jnp.float32), -1.0, 1.0)
        theta = jnp.arccos(lg32)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(y.reshape(-1), lg.shape[-1],
                                dtype=jnp.float32)
        adj = jnp.where(onehot > 0, target, lg32) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        loss = -jnp.sum(onehot * logp, axis=-1, keepdims=True)
        sm = jnp.exp(logp)
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        return (loss, sm)

    loss, sm = apply("margin_cross_entropy", _fn, _t(logits), _t(label))
    if return_softmax:
        return loss, sm
    return loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample class centers: positives plus random negatives (reference:
    nn/functional/common.py class_center_sample op, PartialFC).  Host-side
    sampling (eager; the result feeds a sharded lm-head matmul)."""
    lab = np.asarray(_t(label).numpy()).reshape(-1)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos)
        extra = np.random.choice(neg_pool, num_samples - len(pos),
                                 replace=False)
        sampled = np.concatenate([pos, extra])
    sampled = np.sort(sampled)
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return to_tensor(remap[lab]), to_tensor(sampled.astype(np.int64))


# --------------------------------------------------------------------------
# sequence / decoding
# --------------------------------------------------------------------------

def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (reference: nn/functional/extension.py
    gather_tree -> gather_tree op): ids/parents [T, B, beam] -> full
    sequences following parent pointers from the last step."""

    def _fn(idv, par):
        T = idv.shape[0]

        def body(carry, t):
            beam_idx = carry  # [B, beam] which source beam each final
            step_ids = jnp.take_along_axis(idv[t], beam_idx, axis=-1)
            next_idx = jnp.take_along_axis(par[t], beam_idx, axis=-1)
            return next_idx, step_ids

        init = jnp.broadcast_to(jnp.arange(idv.shape[2]),
                                idv.shape[1:]).astype(par.dtype)
        _, rev = jax.lax.scan(body, init, jnp.arange(T - 1, -1, -1))
        return rev[::-1]

    return apply("gather_tree", _fn, _t(ids), _t(parents))


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention with a CSR sparsity pattern (reference:
    nn/functional/sparse_attention.py -> sparse_attention CUDA op).

    TPU-native: a CSR-driven *mask* over the dense flash path — XLA fuses
    the mask; the pattern is static per compile, which is the same
    contract as the reference (fixed CSR per layer)."""

    def _fn(q, k, v, off, cols, *masks):
        B, H, M, D = q.shape
        N = k.shape[2]
        nnz = cols.shape[-1]
        j = jnp.arange(nnz)

        def one_mask(o, c):
            # row id of each nnz via searchsorted over the offset vector
            rows = jnp.searchsorted(o, j, side="right") - 1
            return jnp.zeros((M, N), bool).at[rows, c].set(True)

        # per-(batch, head) CSR patterns
        mask = jax.vmap(one_mask)(off.reshape(B * H, -1),
                                  cols.reshape(B * H, -1)).reshape(B, H, M, N)
        scores = jnp.einsum("bhmd,bhnd->bhmn", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / math.sqrt(D)
        scores = jnp.where(mask, scores, -1e30)
        mi = 0
        if key_padding_mask is not None:
            kpm = masks[mi]
            mi += 1
            if kpm.dtype == jnp.bool_:
                scores = jnp.where(kpm[:, None, None, :], scores, -1e30)
            else:  # float mask: 0 keeps, nonzero-negative masks (additive)
                scores = scores + kpm[:, None, None, :].astype(jnp.float32)
        if attn_mask is not None:
            am = masks[mi]
            if am.dtype == jnp.bool_:
                scores = jnp.where(am, scores, -1e30)
            else:
                scores = scores + am.astype(jnp.float32)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        return jnp.einsum("bhmn,bhnd->bhmd", probs, v)

    args = [_t(query), _t(key), _t(value), _t(sparse_csr_offset),
            _t(sparse_csr_columns)]
    if key_padding_mask is not None:
        args.append(_t(key_padding_mask))
    if attn_mask is not None:
        args.append(_t(attn_mask))
    return apply("sparse_attention", _fn, *args)


def tanh_(x, name=None):
    """In-place tanh (parity alias; reference exports it from functional)."""
    return x.tanh_()
