"""paddle.nn.functional surface (reference: python/paddle/nn/functional/)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import scaled_dot_product_attention, flash_attention  # noqa: F401
from .extras import (  # noqa: F401
    affine_grid, class_center_sample, diag_embed, dice_loss, gather_tree,
    hsigmoid_loss, margin_cross_entropy, max_unpool1d, max_unpool2d,
    max_unpool3d, npair_loss, sparse_attention, tanh_, temporal_shift,
    zeropad2d,
)
