# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Common functionals: linear, dropout, embedding, pad, interpolate, etc.
(reference: python/paddle/nn/functional/common.py, input.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply, is_grad_enabled
from ...core.dtype import to_np
from ...core.tensor import Tensor, to_tensor
from ...ops import random as rnd
from ...ops.manipulation import pad as _pad_op


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _linear2_impl(v, w):
    return v @ w


def _linear3_impl(v, w, b):
    return v @ w + b


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle weight layout [in_features, out_features]."""
    if bias is None:
        return apply("linear", _linear2_impl, _t(x), _t(weight))
    return apply("linear", _linear3_impl, _t(x), _t(weight), _t(bias))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return _t(x)
    key = rnd.next_key()

    def _dropout(v):
        if axis is None:
            keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            mask_shape = [v.shape[i] if i in axes else 1 for i in range(v.ndim)]
            keep = jax.random.bernoulli(key, 1.0 - p, tuple(mask_shape))
        scale = 1.0 / (1.0 - p) if mode == "upscale_in_train" else 1.0
        return jnp.where(keep, v * scale, 0.0).astype(v.dtype)
    return apply("dropout", _dropout, _t(x))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axes = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axes, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axes = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axes, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return _t(x)
    key = rnd.next_key()

    def _ad(v):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / (1.0 - p) / jnp.sqrt(1.0 + p * alpha_p ** 2 / (1.0 - p)))
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)
    return apply("alpha_dropout", _ad, _t(x))


def _embedding_impl(idx, w, padding_idx=None):
    out = jnp.take(w, idx, axis=0)
    if padding_idx is not None:
        mask = (idx == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return apply("embedding", _embedding_impl, _t(x), _t(weight),
                 padding_idx=padding_idx)


def one_hot(x, num_classes, name=None):
    from ...ops.creation import one_hot as _oh

    return _oh(x, num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def _ls(lab):
        k = lab.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._value if isinstance(prior_dist, Tensor) else prior_dist
            return (1 - epsilon) * lab + epsilon * pd
        return (1 - epsilon) * lab + epsilon / k
    return apply("label_smooth", _ls, _t(label))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    return _pad_op(x, pad, mode=mode, value=value, data_format=data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def _cos(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return apply("cosine_similarity", _cos, _t(x1), _t(x2))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _norm(v):
        n = jnp.linalg.norm(v, ord=p, axis=axis, keepdims=True)
        return v / jnp.maximum(n, epsilon)
    return apply("normalize", _norm, _t(x))


def _resize_taps(in_size, out_size, align_corners, cubic, align_mode=0):
    """(idx [out, T] int32, w [out, T] f32): separable interpolation taps
    matching the reference/torch coordinate rules — align_corners=True
    maps i -> i*(in-1)/(out-1); False uses half-pixel centers; bicubic is
    the Keys kernel with a=-0.75 (jax.image uses a=-0.5, which silently
    diverges from every torch/paddle-trained vision model)."""
    i = np.arange(out_size, dtype=np.float64)
    if align_corners and out_size > 1:
        c = i * ((in_size - 1) / (out_size - 1))
    elif align_mode == 1 and not cubic:
        # reference align_mode=1 (interpolate_op.h): src = ratio*i, no
        # half-pixel offset, for the linear modes only
        c = i * (in_size / out_size)
    else:
        c = (i + 0.5) * (in_size / out_size) - 0.5
    i0 = np.floor(c)
    f = c - i0
    if cubic:
        a = -0.75

        def k(d):
            d = np.abs(d)
            return np.where(
                d <= 1, ((a + 2) * d - (a + 3)) * d * d + 1,
                np.where(d < 2, ((a * d - 5 * a) * d + 8 * a) * d - 4 * a,
                         0.0))

        offs = np.arange(-1, 3)
        idx = i0[:, None] + offs[None, :]
        w = k(f[:, None] - offs[None, :])
    else:
        offs = np.arange(0, 2)
        idx = i0[:, None] + offs[None, :]
        w = np.stack([1.0 - f, f], axis=1)
    idx = np.clip(idx, 0, in_size - 1).astype(np.int32)
    return jnp.asarray(idx), jnp.asarray(w.astype(np.float32))


def _resize_axis(v, axis, out_size, align_corners, cubic, align_mode=0):
    idx, w = _resize_taps(v.shape[axis], out_size, align_corners, cubic,
                          align_mode)
    v0 = jnp.moveaxis(v, axis, 0)
    g = v0[idx]  # [out, T, ...rest]
    wb = w.astype(g.dtype).reshape(w.shape + (1,) * (g.ndim - 2))
    return jnp.moveaxis((g * wb).sum(axis=1), 0, axis)


def _adaptive_mean_axis(v, axis, out_size):
    in_size = v.shape[axis]
    if in_size % out_size == 0:
        k = in_size // out_size
        v0 = jnp.moveaxis(v, axis, 0)
        v0 = v0.reshape((out_size, k) + v0.shape[1:]).mean(axis=1)
        return jnp.moveaxis(v0, 0, axis)
    # torch adaptive rule: window i = [floor(i*in/out), ceil((i+1)*in/out))
    v0 = jnp.moveaxis(v, axis, 0)
    pieces = []
    for i in range(out_size):
        s = (i * in_size) // out_size
        e = -(-((i + 1) * in_size) // out_size)
        pieces.append(v0[s:e].mean(axis=0))
    return jnp.moveaxis(jnp.stack(pieces, axis=0), 0, axis)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """nearest / linear / bilinear / bicubic / trilinear / area resize
    with EXACT reference coordinate semantics (align_corners both ways,
    a=-0.75 bicubic, adaptive-mean area)."""
    def _interp(v):
        is_nchw = data_format[1] == "C"
        spatial_axes = (tuple(range(2, v.ndim)) if is_nchw
                        else tuple(range(1, v.ndim - 1)))
        spatial = tuple(v.shape[a] for a in spatial_axes)
        if size is not None:
            out_spatial = tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                                for s in (size if isinstance(size, (list, tuple))
                                          else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(spatial)
            out_spatial = tuple(int(round(d * float(f)))
                                for d, f in zip(spatial, sf))
        if mode == "nearest":
            # reference interpolate_op.h:99-104 index rule: floor(i*in/out)
            # when align_corners=False, round(i*(in-1)/(out-1)) when True —
            # jax.image.resize uses half-pixel centers, whose indices
            # diverge for non-integer scales (ADVICE r4 medium)
            for a, o in zip(spatial_axes, out_spatial):
                in_size = v.shape[a]
                i = np.arange(o, dtype=np.float64)
                if align_corners and o > 1:
                    # round HALF-UP like the reference's
                    # static_cast<int>(c + 0.5) — np.round's half-to-even
                    # picks the wrong pixel at exact .5 coordinates
                    idx = np.floor(i * (in_size - 1) / (o - 1) + 0.5)
                else:
                    idx = np.floor(i * (in_size / o))
                idx = np.clip(idx, 0, in_size - 1).astype(np.int32)
                v = jnp.take(v, jnp.asarray(idx), axis=a)
            return v
        if mode == "area":
            for a, o in zip(spatial_axes, out_spatial):
                v = _adaptive_mean_axis(v, a, o)
            return v
        cubic = mode == "bicubic"
        dt = v.dtype
        for a, o in zip(spatial_axes, out_spatial):
            v = _resize_axis(v, a, o, align_corners, cubic, align_mode)
        return v.astype(dt)
    return apply("interpolate", _interp, _t(x))


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def _ps(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))
    return apply("pixel_shuffle", _ps, _t(x))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def _pu(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        raise NotImplementedError
    return apply("pixel_unshuffle", _pu, _t(x))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def _cs(v):
        n, c, h, w = v.shape
        v = v.reshape(n, groups, c // groups, h, w)
        v = v.transpose(0, 2, 1, 3, 4)
        return v.reshape(n, c, h, w)
    return apply("channel_shuffle", _cs, _t(x))


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: paddle/fluid/operators/unfold_op.*)."""
    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    p = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 4
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]

    def _unfold(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
        patches = jax.lax.conv_general_dilated_patches(
            v, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: [n, c*kh*kw, oh, ow]
        return patches.reshape(n, c * kh * kw, -1)
    return apply("unfold", _unfold, _t(x))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    p = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 4
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]

    def _fold(v):
        n, ckk, L = v.shape
        c = ckk // (kh * kw)
        out_h = oh + p[0] + p[2]
        out_w = ow + p[1] + p[3]
        nh = (out_h - (dh * (kh - 1) + 1)) // sh + 1
        nw = (out_w - (dw * (kw - 1) + 1)) // sw + 1
        v = v.reshape(n, c, kh, kw, nh, nw)
        out = jnp.zeros((n, c, out_h, out_w), v.dtype)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wj = j * dw
                out = out.at[:, :, hi:hi + nh * sh:sh, wj:wj + nw * sw:sw].add(
                    v[:, :, i, j])
        return out[:, :, p[0]:out_h - p[2], p[1]:out_w - p[3]]
    return apply("fold", _fold, _t(x))


def bilinear(x1, x2, weight, bias=None, name=None):
    def _bilinear(a, b, w, *maybe_bias):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if maybe_bias:
            out = out + maybe_bias[0]
        return out
    if bias is not None:
        return apply("bilinear", _bilinear, _t(x1), _t(x2), _t(weight), _t(bias))
    return apply("bilinear", _bilinear, _t(x1), _t(x2), _t(weight))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample input at normalized grid locations (reference:
    python/paddle/nn/functional/vision.py grid_sample → grid_sampler op).

    x: [N, C, H, W]; grid: [N, Ho, Wo, 2] with (x, y) in [-1, 1]."""
    def _gs(xv, gv):
        N, C, H, W = xv.shape

        def unnorm(coord, size):
            if align_corners:
                return (coord + 1.0) / 2.0 * (size - 1)
            return ((coord + 1.0) * size - 1.0) / 2.0

        gx = unnorm(gv[..., 0], W)  # [N, Ho, Wo]
        gy = unnorm(gv[..., 1], H)
        if padding_mode == "border":
            gx = jnp.clip(gx, 0, W - 1)
            gy = jnp.clip(gy, 0, H - 1)
        elif padding_mode == "reflection":
            def reflect(c, size):
                if align_corners:  # mirror around 0 and size-1
                    span = size - 1
                    if span == 0:  # single-pixel axis: everything maps to 0
                        return jnp.zeros_like(c)
                    c = span - jnp.abs(jnp.mod(c, 2 * span) - span)
                else:  # mirror around -0.5 and size-0.5
                    span = size
                    c = span - jnp.abs(jnp.mod(c + 0.5, 2 * span)
                                       - span) - 0.5
                return jnp.clip(c, 0, size - 1)
            gx = reflect(gx, W)
            gy = reflect(gy, H)

        def sample_img(img, sy, sx):
            # img [C, H, W]; sy/sx [Ho, Wo]
            if mode == "nearest":
                yi = jnp.clip(jnp.round(sy), 0, H - 1).astype(jnp.int32)
                xi = jnp.clip(jnp.round(sx), 0, W - 1).astype(jnp.int32)
                v = img[:, yi, xi]
                if padding_mode == "zeros":
                    ok = ((sy >= -0.5) & (sy <= H - 0.5) & (sx >= -0.5)
                          & (sx <= W - 0.5)).astype(img.dtype)
                    v = v * ok[None]
                return v
            y0 = jnp.floor(sy)
            x0 = jnp.floor(sx)
            wy = sy - y0
            wx = sx - x0

            def corner(yi, xi):
                ok = ((yi >= 0) & (yi <= H - 1) & (xi >= 0)
                      & (xi <= W - 1)).astype(img.dtype)
                yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
                xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
                v = img[:, yc, xc]
                if padding_mode == "zeros":
                    v = v * ok[None]
                return v

            return (corner(y0, x0) * (1 - wy) * (1 - wx)
                    + corner(y0, x0 + 1) * (1 - wy) * wx
                    + corner(y0 + 1, x0) * wy * (1 - wx)
                    + corner(y0 + 1, x0 + 1) * wy * wx)

        return jax.vmap(sample_img)(xv, gy, gx)

    return apply("grid_sample", _gs, _t(x), _t(grid))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Length tensor -> padding mask (reference:
    python/paddle/fluid/layers/sequence_lod.py sequence_mask):
    out[..., j] = j < x[...]."""
    from ...core.dtype import to_np

    def _mask(lens, maxlen_val):
        m = int(maxlen_val)
        rng = jnp.arange(m)
        return (rng[None, :] < lens.reshape(-1, 1)).reshape(
            tuple(lens.shape) + (m,)).astype(to_np(dtype))

    lens = _t(x)
    if maxlen is None:
        if isinstance(lens._value, jax.core.Tracer):
            raise ValueError(
                "sequence_mask without maxlen has a data-dependent output "
                "shape; pass maxlen explicitly under jit")
        import numpy as np

        maxlen = int(np.asarray(lens._value).max())
    return apply("sequence_mask", _mask, lens, maxlen_val=int(maxlen))


# --------------------------------------------------------------------------
# Analytic eager-VJP rules (core/dispatch.py register_eager_vjp) for the
# training hot path: linear and embedding dominate transformer eager steps
# (VERDICT r3 #2; reference analog: codegen'd matmul_grad / lookup_table_grad).
def _linear2_rule(vals, attrs):
    if attrs:
        return None
    v, w = vals
    if v.ndim < 2 or w.ndim != 2:
        return None
    out = v @ w

    def vjp(ct):
        gx = ct @ w.T
        v2 = v.reshape(-1, v.shape[-1])
        ct2 = ct.reshape(-1, ct.shape[-1])
        gw = v2.T @ ct2
        return (gx.astype(v.dtype), gw.astype(w.dtype))
    return out, vjp


def _linear3_rule(vals, attrs):
    if attrs:
        return None
    v, w, b = vals
    if v.ndim < 2 or w.ndim != 2 or b.ndim != 1:
        return None
    out = v @ w + b

    def vjp(ct):
        gx = ct @ w.T
        v2 = v.reshape(-1, v.shape[-1])
        ct2 = ct.reshape(-1, ct.shape[-1])
        gw = v2.T @ ct2
        gb = ct2.sum(axis=0)
        return (gx.astype(v.dtype), gw.astype(w.dtype), gb.astype(b.dtype))
    return out, vjp


def _embedding_rule(vals, attrs):
    idx, w = vals
    if not jnp.issubdtype(idx.dtype, jnp.integer) or w.ndim != 2:
        return None
    pad = attrs.get("padding_idx")
    out = _embedding_impl(idx, w, padding_idx=pad)

    def vjp(ct):
        c = ct
        if pad is not None:
            c = jnp.where((idx == pad)[..., None], 0.0, c)
        gw = jnp.zeros_like(w).at[idx].add(c.astype(w.dtype))
        # int ids are never differentiable; position 0 is unused by the
        # dispatch selection but must exist in the tuple
        return (None, gw)
    return out, vjp


def _register_common_rules():
    from ...core.dispatch import register_eager_vjp

    register_eager_vjp("linear", _linear2_impl, _linear2_rule)
    register_eager_vjp("linear", _linear3_impl, _linear3_rule)
    register_eager_vjp("embedding", _embedding_impl, _embedding_rule)


_register_common_rules()
