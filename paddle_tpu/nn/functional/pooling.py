# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Pooling functionals via XLA reduce_window
(reference: python/paddle/nn/functional/pooling.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply
from ...core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v) if len(v) == n else tuple(
            int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _pool_pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if all(isinstance(p, (list, tuple)) for p in padding):
        if len(padding) == n + 2:
            padding = padding[2:]
        return [tuple(p) for p in padding]
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _ceil_extras(in_sizes, window, strides, pads):
    """Right-edge padding extension implementing ceil_mode: the last
    partial window is included, but (reference/torch rule) a window that
    would START beyond input+left-pad is dropped."""
    extras = []
    for size, k, s, (pl, pr) in zip(in_sizes, window, strides, pads):
        eff = size + pl + pr
        out = -(-(eff - k) // s) + 1  # ceil
        if (out - 1) * s >= size + pl:
            out -= 1
        extras.append(max(0, (out - 1) * s + k - eff))
    return extras


def _reduce_window(v, init, op, window, strides, pads, channel_last, n):
    if channel_last:
        dims = (1,) + window + (1,)
        strd = (1,) + strides + (1,)
        padc = [(0, 0)] + list(pads) + [(0, 0)] if not isinstance(pads, str) else pads
    else:
        dims = (1, 1) + window
        strd = (1, 1) + strides
        padc = [(0, 0), (0, 0)] + list(pads) if not isinstance(pads, str) else pads
    if isinstance(padc, str):
        return jax.lax.reduce_window(v, init, op, dims, strd, padc)
    return jax.lax.reduce_window(v, init, op, dims, strd, tuple(padc))


def _max_pool(x, kernel_size, stride, padding, ceil_mode, data_format, n,
              return_mask=False):
    window = _tuplize(kernel_size, n)
    strides = _tuplize(stride if stride is not None else kernel_size, n)
    pads = _pool_pads(padding, n)
    channel_last = data_format[-1] == "C"

    def _fn(v):
        p = pads
        if ceil_mode and not isinstance(p, str):
            sizes = v.shape[1:-1] if channel_last else v.shape[2:]
            extras = _ceil_extras(sizes, window, strides, p)
            p = [(pl, pr + e) for (pl, pr), e in zip(p, extras)]
        out = _reduce_window(v, -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
                             else jnp.iinfo(v.dtype).min,
                             jax.lax.max, window, strides, p, channel_last, n)
        return out.astype(v.dtype)
    out = apply(f"max_pool{n}d", _fn, _t(x))
    if return_mask:
        # indices computed separately (flat index within each window's input)
        idx = _max_pool_indices(x, window, strides, pads, channel_last, n)
        return out, idx
    return out


def _max_pool_indices(x, window, strides, pads, channel_last, n):
    """Flat input-spatial index of each window max (for MaxUnpool)."""
    def _fn(v):
        if channel_last or n != 2:
            raise NotImplementedError("return_mask only for NCHW 2d pooling")
        kh, kw = window
        pad_cfg = pads if isinstance(pads, str) else tuple(pads)
        patches = jax.lax.conv_general_dilated_patches(
            v, window, strides, pad_cfg,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        nb, ckk, oh, ow = patches.shape
        c = v.shape[1]
        patches = patches.reshape(nb, c, kh * kw, oh, ow)
        widx = jnp.argmax(patches, axis=2)  # index within window
        wi, wj = widx // kw, widx % kw
        pt = 0 if isinstance(pads, str) else pads[0][0]
        pl = 0 if isinstance(pads, str) else pads[1][0]
        oh_idx = jnp.arange(oh).reshape(1, 1, oh, 1)
        ow_idx = jnp.arange(ow).reshape(1, 1, 1, ow)
        h = oh_idx * strides[0] - pt + wi
        w_ = ow_idx * strides[1] - pl + wj
        return (h * v.shape[3] + w_).astype(jnp.int64)
    return apply("max_pool_indices", _fn, _t(x), _differentiable=False)


def _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive,
              data_format, n, divisor_override=None):
    window = _tuplize(kernel_size, n)
    strides = _tuplize(stride if stride is not None else kernel_size, n)
    pads = _pool_pads(padding, n)
    channel_last = data_format[-1] == "C"

    def _fn(v):
        p = pads
        extras = None
        if ceil_mode and not isinstance(p, str):
            sizes = v.shape[1:-1] if channel_last else v.shape[2:]
            extras = _ceil_extras(sizes, window, strides, p)
            p = [(pl, pr + e) for (pl, pr), e in zip(pads, extras)]
        summed = _reduce_window(v.astype(jnp.float32), 0.0, jax.lax.add, window,
                                strides, p, channel_last, n)
        if divisor_override:
            denom = float(divisor_override)
            out = summed / denom
        elif exclusive and not isinstance(p, str):
            ones = jnp.ones_like(v, jnp.float32)
            denom = _reduce_window(ones, 0.0, jax.lax.add, window, strides, p,
                                   channel_last, n)
            out = summed / denom
        elif extras is not None and any(extras):
            # include-pad + ceil: base pads COUNT in the divisor but the
            # ceil extension does not (reference divisor rule) — count
            # via ones extended by base pads as ones
            ones = jnp.ones_like(v, jnp.float32)
            if channel_last:
                base = [(0, 0)] + [(pl, pr) for pl, pr in pads] + [(0, 0)]
                ext = ((0, 0),) + tuple((0, e) for e in extras) + ((0, 0),)
                dims = (1,) + window + (1,)
                strd = (1,) + strides + (1,)
            else:
                base = [(0, 0), (0, 0)] + [(pl, pr) for pl, pr in pads]
                ext = ((0, 0), (0, 0)) + tuple((0, e) for e in extras)
                dims = (1, 1) + window
                strd = (1, 1) + strides
            ones = jnp.pad(ones, base, constant_values=1.0)
            denom = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strd,
                                          ext)
            out = summed / denom
        else:
            out = summed / float(np.prod(window))
        return out.astype(v.dtype)
    return apply(f"avg_pool{n}d", _fn, _t(x))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    if return_mask and df == "NCW":
        # indices come from the 2d path on an unsqueezed height dim; the
        # flat h*W+w index collapses to the 1d position when h == 0
        from ...ops.manipulation import squeeze, unsqueeze

        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        s = stride if stride is not None else k
        s = s if isinstance(s, int) else s[0]
        p = padding if isinstance(padding, int) else padding[0]
        out, idx = _max_pool(unsqueeze(_t(x), 2), (1, k), (1, s), (0, p),
                             ceil_mode, "NCHW", 2, True)
        return squeeze(out, 2), squeeze(idx, 2)
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, df, 1,
                     return_mask)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, data_format, 2,
                     return_mask)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, data_format, 3,
                     return_mask)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive, df, 1)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive,
                     data_format, 2, divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive,
                     data_format, 3, divisor_override)


def _adaptive_starts_ends(in_size, out_size):
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, n, reduce_fn, data_format):
    if data_format[-1] == "C":
        raise NotImplementedError("adaptive pool with channel_last")
    out_sizes = _tuplize(output_size, n)

    def _fn(v):
        spatial = v.shape[2:]
        if all(s % o == 0 for s, o in zip(spatial, out_sizes)):
            # uniform windows: single reshape+reduce (fast path; global pool is
            # out_size=1)
            new_shape = list(v.shape[:2])
            red_axes = []
            for i, (s, o) in enumerate(zip(spatial, out_sizes)):
                new_shape += [o, s // o]
                red_axes.append(2 + 2 * i + 1)
            return reduce_fn(v.reshape(new_shape), tuple(red_axes))
        # general case: per-output-cell windows (static python loop, XLA unrolls)
        slices = [_adaptive_starts_ends(s, o) for s, o in zip(spatial, out_sizes)]

        def cell(idx):
            sl = tuple(
                slice(slices[d][0][idx[d]], slices[d][1][idx[d]])
                for d in range(n))
            return reduce_fn(v[(slice(None), slice(None)) + sl],
                             tuple(range(2, 2 + n)))
        from itertools import product

        cells = [cell(idx) for idx in product(*[range(o) for o in out_sizes])]
        out = jnp.stack(cells, axis=-1)
        return out.reshape(v.shape[:2] + out_sizes)
    return apply(f"adaptive_pool{n}d", _fn, _t(x))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, lambda v, a: jnp.mean(v, axis=a),
                          "NCW")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, lambda v, a: jnp.mean(v, axis=a),
                          data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, lambda v, a: jnp.mean(v, axis=a),
                          data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, lambda v, a: jnp.max(v, axis=a),
                          "NCW")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, lambda v, a: jnp.max(v, axis=a),
                          "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, lambda v, a: jnp.max(v, axis=a),
                          "NCDHW")
