# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Convolution functionals lowered to XLA conv_general_dilated
(reference: python/paddle/nn/functional/conv.py; kernels in
/root/reference/paddle/phi/kernels/gpu/conv_*).  Paddle layouts: input NCHW
(or NHWC via data_format), weight OIHW.  XLA's layout assignment re-tiles for
the MXU, so we keep the API layout and let the compiler choose physical
layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return tuple(int(v) for _ in range(n))


def _padding(padding, n):
    """paddle padding: int, list of ints (per spatial dim), pairs, or SAME/VALID."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if all(isinstance(p, (list, tuple)) for p in padding):
        # may include batch/channel dims
        if len(padding) == n + 2:
            padding = padding[2:]
        return [tuple(p) for p in padding]
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format, op_name):
    strides = _tuplize(stride, n)
    dilations = _tuplize(dilation, n)
    pads = _padding(padding, n)
    channel_last = data_format[-1] == "C"
    spatial = "DHW"[3 - n:]
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    dn = (lhs_spec, "OI" + spatial, lhs_spec)

    def _fn(v, w, *maybe_b):
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=strides, padding=pads,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else -1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply(op_name, _fn, _t(x), _t(weight), _t(bias))
    return apply(op_name, _fn, _t(x), _t(weight))


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df,
                 "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, data_format, op_name, output_size=None):
    strides = _tuplize(stride, n)
    dilations = _tuplize(dilation, n)
    pads = _padding(padding, n)
    out_pads = _tuplize(output_padding, n)
    channel_last = data_format[-1] == "C"
    spatial = "DHW"[3 - n:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    # paddle transpose-conv weight layout: [in_channels, out_channels/groups,
    # *k].  transpose_kernel=True makes lax.conv_transpose the exact
    # GRADIENT of a forward conv (kernel spatially flipped + IO swapped),
    # matching reference/torch semantics — so the spec below describes the
    # FORWARD kernel being transposed ("OI...": dim0 = lhs channels after
    # the swap).  Without it the kernel is applied unflipped and every
    # transpose-conv output silently diverges.
    dn = (lhs_spec, "OI" + spatial, lhs_spec)

    def _fn(v, w, *maybe_b):
        if isinstance(pads, str):
            pad_cfg = pads
        else:
            # conv_transpose padding semantics: output trimmed by `pad` each side
            k = [w.shape[2 + i] for i in range(n)]
            pad_cfg = [
                (dilations[i] * (k[i] - 1) - pads[i][0],
                 dilations[i] * (k[i] - 1) - pads[i][1] + out_pads[i])
                for i in range(n)
            ]
        if groups > 1:
            # split the input-channel axis per group
            ci_axis = 1 if not channel_last else v.ndim - 1
            v_groups = jnp.split(v, groups, axis=ci_axis)
            w_groups = jnp.split(w, groups, axis=0)
            outs = [
                jax.lax.conv_transpose(
                    vg, wg, strides=strides, padding=pad_cfg,
                    rhs_dilation=dilations, dimension_numbers=dn,
                    transpose_kernel=True)
                for vg, wg in zip(v_groups, w_groups)
            ]
            out = jnp.concatenate(outs, axis=ci_axis)
        else:
            out = jax.lax.conv_transpose(
                v, w, strides=strides, padding=pad_cfg,
                rhs_dilation=dilations, dimension_numbers=dn,
                transpose_kernel=True)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else -1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply(op_name, _fn, _t(x), _t(weight), _t(bias))
    return apply(op_name, _fn, _t(x), _t(weight))


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, df, "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, "conv3d_transpose")
