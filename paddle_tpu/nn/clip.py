# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Gradient clipping (reference: python/paddle/fluid/clip.py:
ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "trainable", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "trainable", True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._value.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip.  Under hybrid parallelism the distributed optimizer
    extends the squared-norm sum with cross-mesh psums (reference:
    HybridParallelOptimizer grad clip across mp/pp/sharding axes)."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = clip_norm

    def _global_norm(self, params_grads):
        sq = [jnp.sum(jnp.square(g._value.astype(jnp.float32)))
              for p, g in params_grads
              if g is not None and getattr(p, "trainable", True)]
        if not sq:
            return None
        total = sq[0]
        for s in sq[1:]:
            total = total + s
        return jnp.sqrt(total)

    def _clip(self, params_grads):
        global_norm = self._global_norm(params_grads)
        if global_norm is None:
            return params_grads
        scale = jnp.minimum(
            self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "trainable", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g._value.astype(jnp.float32)),
                                  norm_type)) for g in grads),
            1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._value = (p.grad._value * scale).astype(p.grad._value.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)
