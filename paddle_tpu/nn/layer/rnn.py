# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

The reference dispatches to cuDNN RNN kernels; the TPU-native design lowers
the time loop to XLA While via jax.lax.scan, which is how recurrences are
expressed for the MXU (weights stay resident, steps pipeline).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as init
from .layers import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ... import zeros

        B = batch_ref.shape[batch_dim_idx]
        return zeros([B, self.hidden_size])


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _cell(x, h, wi, wh, bi, bh):
            z = x @ wi.T + bi + h @ wh.T + bh
            return jnp.tanh(z) if self.activation == "tanh" else jax.nn.relu(z)
        h = apply("simple_rnn_cell", _cell, inputs, states, self.weight_ih,
                  self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def _cell(x, h_, c_, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h_ @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = f * c_ + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c
        new_h, new_c = apply("lstm_cell", _cell, inputs, h, c, self.weight_ih,
                             self.weight_hh, self.bias_ih, self.bias_hh)
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _cell(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ig = jnp.split(gi, 3, axis=-1)
            hr, hz, hg = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            g = jnp.tanh(ig + r * hg)
            return (1 - z) * g + z * h
        h = apply("gru_cell", _cell, inputs, states, self.weight_ih,
                  self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h


class RNN(Layer):
    """Run a cell over time via lax.scan (reference RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # straightforward python loop (eager) — static unroll under jit;
        # the stacked _RNNBase below uses lax.scan for the fused path
        from ...ops.manipulation import stack

        if not self.time_major:
            steps = inputs.shape[1]
            get = lambda t: inputs[:, t]
        else:
            steps = inputs.shape[0]
            get = lambda t: inputs[t]
        states = initial_states
        outs = []
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for t in order:
            out, states = self.cell(get(t), states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        outputs = stack(outs, axis=0 if self.time_major else 1)
        return outputs, states


class _RNNBase(Layer):
    """Stacked multi-layer bi-directional RNN lowered with lax.scan."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}[mode]

        std = 1.0 / math.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for direction_idx in range(self.bidirect):
                in_size = input_size if layer == 0 \
                    else hidden_size * self.bidirect
                suffix = "_reverse" if direction_idx else ""
                wi = self.create_parameter([gate_mult * hidden_size, in_size],
                                           weight_ih_attr,
                                           default_initializer=u)
                wh = self.create_parameter(
                    [gate_mult * hidden_size, hidden_size], weight_hh_attr,
                    default_initializer=u)
                bi = self.create_parameter([gate_mult * hidden_size],
                                           bias_ih_attr, is_bias=True,
                                           default_initializer=u)
                bh = self.create_parameter([gate_mult * hidden_size],
                                           bias_hh_attr, is_bias=True,
                                           default_initializer=u)
                names = [f"weight_ih_l{layer}{suffix}",
                         f"weight_hh_l{layer}{suffix}",
                         f"bias_ih_l{layer}{suffix}",
                         f"bias_hh_l{layer}{suffix}"]
                for n, p in zip(names, [wi, wh, bi, bh]):
                    self.add_parameter(n, p)
                self._all_weights.append(names)

    def _cell_step(self, mode):
        if mode == "LSTM":
            def step(x, state, wi, wh, bi, bh):
                h, c = state
                gates = x @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                           jax.nn.sigmoid(o))
                g = jnp.tanh(g)
                c = f * c + i * g
                h = o * jnp.tanh(c)
                return h, (h, c)
        elif mode == "GRU":
            def step(x, state, wi, wh, bi, bh):
                h = state
                gi = x @ wi.T + bi
                gh = h @ wh.T + bh
                ir, iz, ig = jnp.split(gi, 3, axis=-1)
                hr, hz, hg = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                g = jnp.tanh(ig + r * hg)
                h = (1 - z) * g + z * h
                return h, h
        else:
            act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu

            def step(x, state, wi, wh, bi, bh):
                h = act(x @ wi.T + bi + state @ wh.T + bh)
                return h, h
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.mode
        is_lstm = mode == "LSTM"
        step = self._cell_step(mode)
        time_major = self.time_major
        nl, bd, hs = self.num_layers, self.bidirect, self.hidden_size

        weights = []
        for names in self._all_weights:
            weights.extend(self._parameters[n] for n in names)

        def _run(v, *flat_w):
            x = v if time_major else jnp.swapaxes(v, 0, 1)  # [T, B, I]
            B = x.shape[1]
            idx = 0
            final_h, final_c = [], []
            for layer in range(nl):
                outs_dir = []
                for d in range(bd):
                    wi, wh, bi, bh = flat_w[idx:idx + 4]
                    idx += 4
                    h0 = jnp.zeros((B, hs), v.dtype)
                    state0 = (h0, h0) if is_lstm else h0
                    xs = x[::-1] if d == 1 else x

                    def scan_fn(state, xt, wi=wi, wh=wh, bi=bi, bh=bh):
                        out, new_state = step(xt, state, wi, wh, bi, bh)
                        return new_state, out

                    last_state, ys = jax.lax.scan(scan_fn, state0, xs)
                    if d == 1:
                        ys = ys[::-1]
                    outs_dir.append(ys)
                    if is_lstm:
                        final_h.append(last_state[0])
                        final_c.append(last_state[1])
                    else:
                        final_h.append(last_state)
                x = outs_dir[0] if bd == 1 else jnp.concatenate(outs_dir, -1)
            out = x if time_major else jnp.swapaxes(x, 0, 1)
            h_stack = jnp.stack(final_h, 0)
            if is_lstm:
                return out, h_stack, jnp.stack(final_c, 0)
            return out, h_stack

        res = apply(f"rnn_{mode.lower()}", _run, inputs, *weights)
        if is_lstm:
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat

        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        out_fw, st_fw = self.fw(inputs, states_fw)
        out_bw, st_bw = self.bw(inputs, states_bw)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
