"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as init
from .layers import Layer


def _simple(name, fn_name=None, **fixed):
    fn_name = fn_name or name.lower()

    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._args = args
            self._kwargs = {**fixed, **kwargs}
            self._kwargs.pop("name", None)

        def forward(self, x):
            return getattr(F, fn_name)(x, *self._args, **self._kwargs)

    _Act.__name__ = name
    return _Act


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
Sigmoid = _simple("Sigmoid", "sigmoid")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
Hardshrink = _simple("Hardshrink", "hardshrink")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardswish = _simple("Hardswish", "hardswish")
Hardtanh = _simple("Hardtanh", "hardtanh")
ELU = _simple("ELU", "elu")
CELU = _simple("CELU", "celu")
SELU = _simple("SELU", "selu")
GELU = _simple("GELU", "gelu")
Silu = _simple("Silu", "silu")
Swish = _simple("Swish", "swish")
Mish = _simple("Mish", "mish")
Softplus = _simple("Softplus", "softplus")
Softshrink = _simple("Softshrink", "softshrink")
Softsign = _simple("Softsign", "softsign")
LeakyReLU = _simple("LeakyReLU", "leaky_relu")
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu")
Maxout = _simple("Maxout", "maxout")
GLU = _simple("GLU", "glu")


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init_value=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=init.Constant(init_value))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
