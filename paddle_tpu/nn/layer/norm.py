# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.dtype import to_np
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as init
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats

        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=init.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features],
                                                       to_np(self._dtype))))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features],
                                                          to_np(self._dtype))))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (reference: python/paddle/fluid/dygraph/nn.py)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, use_global_stats=False,
                 **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCDHW" else
                         data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.  Under SPMD jit, XLA computes global batch
    stats automatically when the batch axis is sharded (psum of moments);
    eagerly on one chip it equals BatchNorm.  (reference:
    python/paddle/nn/layer/norm.py SyncBatchNorm + c_sync_calc ops)"""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer._num_features, layer._momentum, layer._epsilon,
                      None, None, layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._mean = layer._mean
            new._variance = layer._variance
            return new
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        n = int(jnp.prod(jnp.asarray(self._normalized_shape)))
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [n], attr=weight_attr, default_initializer=init.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([n], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=init.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=init.Constant(1.0))
        else:
            self.scale = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            [h], default_initializer=init.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=init.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        return F.spectral_norm(weight, self.weight_u, self.weight_v, self._dim,
                               self._power_iters, self._eps)


class RMSNorm(Layer):
    """Root-mean-square norm (not in the 2022 reference snapshot, required by
    the Llama family; fused Pallas kernel on TPU via F.rms_norm)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=init.Constant(1.0))

    def forward(self, x):
        from ...core.dispatch import apply
        import jax

        def _rms(v, w):
            var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1,
                           keepdims=True)
            return (v.astype(jnp.float32) * jax.lax.rsqrt(
                var + self._epsilon)).astype(v.dtype) * w
        return apply("rms_norm", _rms, x, self.weight)
