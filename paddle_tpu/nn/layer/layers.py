# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Layer base class.

Capability analog of the reference dygraph Layer
(/root/reference/python/paddle/fluid/dygraph/layers.py: parameters, sublayers,
hooks, state_dict:1397, to, train/eval) — the module system every model is
built on.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...core.dtype import convert_dtype, get_default_dtype, to_np
from ...core.tensor import Parameter, Tensor


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    # global structural version: bumped whenever ANY layer gains a
    # parameter/sublayer/buffer, so jit.to_static can cheaply invalidate
    # its cached state-handle lists (int compare per call; rebuilds are
    # rare post-construction)
    _structure_version = 0

    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        self.training = True
        self._dtype = dtype or get_default_dtype()
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------------------------------------------- attr magic
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            Layer._structure_version += 1
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            Layer._structure_version += 1
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        else:
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor):
                    buffers[name] = value
                    return
                buffers.pop(name)
            if params is not None and name in params and value is None:
                params.pop(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                Layer._structure_version += 1
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # --------------------------------------------------------------- building
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .. import initializer as init

        dtype = dtype or self._dtype
        if default_initializer is None:
            if is_bias:
                default_initializer = init.Constant(0.0)
            else:
                # reference default: ParamAttr._set_default_param_
                # initializer uses Xavier() with uniform=True
                # (param_attr.py:144, initializer.py:506) — U(±sqrt(6/
                # (fan_in+fan_out))), NOT the normal variant
                default_initializer = init.XavierUniform()
        # ParamAttr support: attr may carry name/initializer/trainable
        trainable = True
        if attr is not None and attr is not False:
            if getattr(attr, "initializer", None) is not None:
                default_initializer = attr.initializer
            trainable = getattr(attr, "trainable", True)
        if attr is False:
            return None
        data = default_initializer._generate(tuple(shape), to_np(dtype))
        p = Parameter(data, trainable=trainable)
        if attr is not None and getattr(attr, "name", None):
            p.name = attr.name
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        return Tensor(jnp.zeros((), to_np(dtype or self._dtype)), name=name)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        Layer._structure_version += 1
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        Layer._structure_version += 1
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Tensor, persistable: bool = True):
        Layer._structure_version += 1
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        else:
            tensor.persistable = True
        return tensor

    # --------------------------------------------------------------- traversal
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    full = f"{layer_prefix}.{pname}" if layer_prefix else pname
                    yield full, p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    full = f"{layer_prefix}.{bname}" if layer_prefix else bname
                    yield full, b

    def _walk(self, prefix: str = "", include_sublayers: bool = True):
        yield self._name_scope, prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._walk(sub_prefix, True)

    def children(self) -> Iterator["Layer"]:
        for _, sub in self.named_children():
            yield sub

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        out = []
        for _, _, layer in self._walk("", True):
            if layer is self and not include_self:
                continue
            out.append(layer)
        return out

    def named_sublayers(self, prefix: str = "", include_self: bool = False):
        for _, layer_prefix, layer in self._walk(prefix, True):
            if layer is self and not include_self:
                continue
            yield layer_prefix, layer

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # --------------------------------------------------------------- modes
    def train(self):
        self.training = True
        for sub in self.sublayers():
            sub.training = True
        return self

    def eval(self):
        self.training = False
        for sub in self.sublayers():
            sub.training = False
        return self

    # --------------------------------------------------------------- state
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix,
                                             include_sublayers):
            dest[name] = p
        for _, lp, layer in self._walk(structured_name_prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                full = f"{lp}.{bname}" if lp else bname
                dest[full] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name in state_dict:
                value = state_dict[name]
                arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
                if list(arr.shape) != list(target.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: loaded {list(arr.shape)} "
                        f"vs expected {list(target.shape)}")
                target._value = jnp.asarray(arr, dtype=target._value.dtype)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._convert_dtype(dtype)
        return self

    def _convert_dtype(self, dtype):
        npd = to_np(dtype)
        for p in self.parameters():
            if jnp.issubdtype(p._value.dtype, jnp.floating):
                p._value = p._value.astype(npd)
        for b in self.buffers():
            if jnp.issubdtype(b._value.dtype, jnp.floating):
                b._value = b._value.astype(npd)
        for layer in self.sublayers(include_self=True):
            layer._dtype = convert_dtype(dtype).name
        return self

    def astype(self, dtype):
        return self._convert_dtype(dtype)

    def float(self):
        return self._convert_dtype("float32")

    def bfloat16(self):
        return self._convert_dtype("bfloat16")

    def half(self):
        return self._convert_dtype("float16")

    # --------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # --------------------------------------------------------------- forward
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def full_name(self):
        return self._name_scope

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class ParamAttr:
    """paddle.ParamAttr analog (reference: python/paddle/fluid/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
