# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Seq2seq decoding API (reference: python/paddle/nn/decode.py —
BeamSearchDecoder over an RNN cell + dynamic_decode driver; the static
path compiles to a While op, the dygraph path is a host loop).

TPU-native: the host loop is retained for eager use (the reference's
dygraph behavior); steps are compiled by XLA per shape, and the final
backtrace reuses functional.gather_tree.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor, to_tensor
from ..functional.extras import gather_tree


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class BeamSearchDecoder:
    """Beam search over a step cell (reference: nn/decode.py
    BeamSearchDecoder: _expand_to_beam_size/tile_beam_merge_with_batch,
    step -> topk over beam*vocab with parent pointers)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] (repeat each batch row beam times)."""
        def _fn(v):
            return jnp.repeat(v, beam_size, axis=0)

        return apply("tile_beam_merge_with_batch", _fn,
                     x if isinstance(x, Tensor) else to_tensor(x))

    # -- decoder protocol --
    def initialize(self, initial_cell_states):
        states = jax.tree_util.tree_map(
            lambda v: jnp.repeat(_val(v), self.beam_size, axis=0),
            initial_cell_states)
        some = jax.tree_util.tree_leaves(states)[0]
        B = some.shape[0] // self.beam_size
        ids = jnp.full((B, self.beam_size), self.start_token, jnp.int64)
        # beam 0 active, others dead (-inf) so step 1 expands one beam
        scores = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1)), (B, 1))
        finished = jnp.zeros((B, self.beam_size), bool)
        return ids, (states, scores, finished), finished

    def step(self, time, inputs, states):
        cell_states, scores, finished = states
        B, beam = inputs.shape
        flat_ids = inputs.reshape(B * beam)
        if self.embedding_fn is not None:
            emb = self.embedding_fn(Tensor(flat_ids))
            emb = _val(emb)
        else:
            emb = flat_ids
        cell_out, next_states = self.cell(Tensor(emb), cell_states)
        out = _val(self.output_fn(cell_out) if self.output_fn else cell_out)
        logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
        V = logp.shape[-1]
        logp = logp.reshape(B, beam, V)
        # a finished beam may only continue with end_token at zero cost,
        # freezing its score (reference locks finished beams the same way)
        end_only = jnp.full((V,), -1e9).at[self.end_token].set(0.0)
        logp = jnp.where(finished[..., None], end_only, logp)
        total = scores[..., None] + logp                  # [B, beam, V]
        flat = total.reshape(B, beam * V)
        top_scores, top_idx = jax.lax.top_k(flat, beam)   # [B, beam]
        parents = (top_idx // V).astype(jnp.int64)
        tokens = (top_idx % V).astype(jnp.int64)
        # gather cell states along the chosen parent beams
        b_idx = (jnp.arange(B)[:, None] * beam + parents).reshape(-1)
        next_states = jax.tree_util.tree_map(
            lambda v: _val(v)[b_idx], next_states)
        parent_finished = jnp.take_along_axis(finished, parents, axis=-1)
        next_finished = parent_finished | (tokens == self.end_token)
        return ((tokens, parents, top_scores),
                (next_states, top_scores, next_finished), tokens,
                next_finished)


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   is_test=False, return_length=False, **kwargs):
    """Drive a decoder until every beam finishes or max_step_num
    (reference: nn/decode.py dynamic_decode).  Returns (ids, scores) with
    ids backtraced via gather_tree, [B, beam, T] batch-major by default."""
    inputs, states, finished = decoder.initialize(inits)
    step_ids, step_parents = [], []
    scores = None
    t = 0
    fin_acc = finished
    lengths = jnp.zeros(fin_acc.shape, jnp.int64)
    while True:
        (tokens, parents, scores), states, inputs, finished = decoder.step(
            t, inputs if not isinstance(inputs, Tensor) else _val(inputs),
            states)
        step_ids.append(tokens)
        step_parents.append(parents)
        lengths = jnp.where(fin_acc, lengths, lengths + 1)
        fin_acc = fin_acc | finished
        t += 1
        if bool(jnp.all(fin_acc)) or (max_step_num is not None
                                      and t >= max_step_num):
            break
    ids = jnp.stack(step_ids)          # [T, B, beam]
    parents = jnp.stack(step_parents)
    traced = _val(gather_tree(Tensor(ids), Tensor(parents)))  # [T, B, beam]
    if not output_time_major:
        traced = jnp.transpose(traced, (1, 2, 0))  # [B, beam, T]
    out = (Tensor(traced), Tensor(scores))
    if return_length:
        return out + (Tensor(lengths),)
    return out
