# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Weight initializers (reference: python/paddle/nn/initializer/,
python/paddle/fluid/initializer.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops import random as rnd


def _fan_in_out(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weights are [in, out]
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def _generate(self, shape, np_dtype):
        raise NotImplementedError

    def __call__(self, param, block=None):
        param._value = jnp.asarray(
            self._generate(tuple(param.shape), param._value.dtype))
        return param


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, np_dtype):
        return jnp.full(shape, self.value, np_dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, np_dtype):
        key = rnd.next_key()
        return (jax.random.normal(key, shape, jnp.float32) * self.std
                + self.mean).astype(np_dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, np_dtype):
        key = rnd.next_key()
        return (jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32)
                * self.std + self.mean).astype(np_dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _generate(self, shape, np_dtype):
        key = rnd.next_key()
        return jax.random.uniform(key, shape, jnp.float32, self.low,
                                  self.high).astype(np_dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, np_dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = rnd.next_key()
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(np_dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, np_dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = rnd.next_key()
        return jax.random.uniform(key, shape, jnp.float32, -limit,
                                  limit).astype(np_dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, np_dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        key = rnd.next_key()
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(np_dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self._fan_in = fan_in
        self.negative_slope = negative_slope

    def _generate(self, shape, np_dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        key = rnd.next_key()
        return jax.random.uniform(key, shape, jnp.float32, -limit,
                                  limit).astype(np_dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _generate(self, shape, np_dtype):
        arr = self.value.numpy() if isinstance(self.value, Tensor) \
            else np.asarray(self.value)
        return jnp.asarray(arr, np_dtype).reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _generate(self, shape, np_dtype):
        key = rnd.next_key()
        return (jax.nn.initializers.orthogonal(self.gain)(
            key, shape, jnp.float32)).astype(np_dtype)


class Bilinear(Initializer):
    """Bilinear-interpolation kernel for transposed-conv upsampling
    (reference: python/paddle/fluid/initializer.py:830 BilinearInitializer
    — every output channel gets the same (K, K) interpolation stencil so
    a Conv2DTranspose with stride=factor upsamples by `factor`)."""

    def _generate(self, shape, np_dtype):
        if len(shape) < 2:
            raise ValueError(
                "Bilinear initializer requires a >=2-D convolution weight")
        k = shape[-1]
        f = math.ceil(k / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        idx = np.arange(int(np.prod(shape)), dtype=np.float64)
        x = idx % shape[-1]
        y = (idx // shape[-1]) % shape[-2]
        w = (1 - np.abs(x / f - c)) * (1 - np.abs(y / f - c))
        return jnp.asarray(w.reshape(shape), np_dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _generate(self, shape, np_dtype):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(mins):
                out[(g * (oc // self.groups) + i, i) + centers] = 1.0
        return jnp.asarray(out, np_dtype)


# paddle.nn.initializer.set_global_initializer parity
_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]
