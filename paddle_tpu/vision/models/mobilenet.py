"""MobileNet V1/V2/V3 (reference: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py, mobilenetv3.py)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import flatten

__all__ = ["MobileNetV1", "MobileNetV2", "MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_small",
           "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU(nn.Layer):
    def __init__(self, in_c, out_c, k=3, stride=1, groups=1, act=nn.ReLU6):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = act() if act is not None else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_ConvBNReLU(3, int(32 * scale), 3, stride=2, act=nn.ReLU)]
        for in_c, out_c, s in cfg:
            ic, oc = int(in_c * scale), int(out_c * scale)
            layers.append(_ConvBNReLU(ic, ic, 3, stride=s, groups=ic,
                                      act=nn.ReLU))
            layers.append(_ConvBNReLU(ic, oc, 1, act=nn.ReLU))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(inp, hidden, 1))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        input_channel = _make_divisible(32 * scale)
        last_channel = _make_divisible(1280 * max(1.0, scale))
        layers = [_ConvBNReLU(3, input_channel, 3, stride=2)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                layers.append(_InvertedResidual(
                    input_channel, out_c, s if i == 0 else 1, t))
                input_channel = out_c
        layers.append(_ConvBNReLU(input_channel, last_channel, 1))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


class _SqueezeExcite(nn.Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, _make_divisible(c // r), 1)
        self.fc2 = nn.Conv2D(_make_divisible(c // r), c, 1)

    def forward(self, x):
        s = self.pool(x)
        s = nn.functional.relu(self.fc1(s))
        s = nn.functional.hardsigmoid(self.fc2(s))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, inp, hidden, out, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        act_layer = nn.Hardswish if act == "HS" else nn.ReLU
        layers = []
        if hidden != inp:
            layers.append(_ConvBNReLU(inp, hidden, 1, act=act_layer))
        layers.append(_ConvBNReLU(hidden, hidden, k, stride=stride,
                                  groups=hidden, act=act_layer))
        if se:
            layers.append(_SqueezeExcite(hidden))
        layers += [nn.Conv2D(hidden, out, 1, bias_attr=False),
                   nn.BatchNorm2D(out)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_SMALL = [
    # k, hidden, out, SE, act, stride
    (3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2),
    (3, 88, 24, False, "RE", 1), (5, 96, 40, True, "HS", 2),
    (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
    (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1),
    (5, 288, 96, True, "HS", 2), (5, 576, 96, True, "HS", 1),
    (5, 576, 96, True, "HS", 1),
]
_V3_LARGE = [
    (3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2),
    (3, 72, 24, False, "RE", 1), (5, 72, 40, True, "RE", 2),
    (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
    (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1),
    (3, 184, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
    (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
    (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1),
    (5, 960, 160, True, "HS", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        in_c = _make_divisible(16 * scale)
        layers = [_ConvBNReLU(3, in_c, 3, stride=2, act=nn.Hardswish)]
        for k, hidden, out, se, act, stride in cfg:
            layers.append(_V3Block(in_c, _make_divisible(hidden * scale),
                                   _make_divisible(out * scale), k, stride, se,
                                   act))
            in_c = _make_divisible(out * scale)
        last_conv = _make_divisible(6 * in_c)
        layers.append(_ConvBNReLU(in_c, last_conv, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_c), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, scale, num_classes, with_pool)


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
