"""DenseNet / GoogLeNet / InceptionV3 / ShuffleNetV2 (reference:
python/paddle/vision/models/{densenet,googlenet,inception,shufflenetv2}.py)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "GoogLeNet", "googlenet", "InceptionV3",
           "inception_v3", "ShuffleNetV2", "shufflenet_v2_x1_0",
           "shufflenet_v2_x0_5"]


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.relu = nn.ReLU()
        self.drop = nn.Dropout(drop_rate) if drop_rate else None

    def forward(self, x):
        h = self.conv1(self.relu(self.norm1(x)))
        h = self.conv2(self.relu(self.norm2(h)))
        if self.drop is not None:
            h = self.drop(h)
        return concat([x, h], axis=1)


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        cfgs = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
                169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
                264: (6, 12, 64, 48)}
        block_config = cfgs[layers]
        num_init = 2 * growth_rate
        if layers == 161:
            growth_rate = 48
            num_init = 96
        self.features = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1))
        ch = num_init
        blocks = []
        for i, n in enumerate(block_config):
            for _ in range(n):
                blocks.append(_DenseLayer(ch, growth_rate, bn_size, dropout))
                ch += growth_rate
            if i < len(block_config) - 1:
                blocks.append(nn.Sequential(
                    nn.BatchNorm2D(ch), nn.ReLU(),
                    nn.Conv2D(ch, ch // 2, 1, bias_attr=False),
                    nn.AvgPool2D(2, 2)))
                ch //= 2
        self.blocks = nn.Sequential(*blocks)
        self.norm_final = nn.BatchNorm2D(ch)
        self.relu = nn.ReLU()
        self.num_classes = num_classes
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.blocks(self.features(x))
        x = self.relu(self.norm_final(x))
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)


class _Inception(nn.Layer):
    def __init__(self, in_c, c1, c2, c3, c4):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_c, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_c, c2[0], 1), nn.ReLU(),
                                nn.Conv2D(c2[0], c2[1], 3, padding=1),
                                nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_c, c3[0], 1), nn.ReLU(),
                                nn.Conv2D(c3[0], c3[1], 5, padding=2),
                                nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                nn.Conv2D(in_c, c4, 1), nn.ReLU())

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1))
        self.inc3 = nn.Sequential(
            _Inception(192, 64, (96, 128), (16, 32), 32),
            _Inception(256, 128, (128, 192), (32, 96), 64),
            nn.MaxPool2D(3, 2, padding=1))
        self.inc4 = nn.Sequential(
            _Inception(480, 192, (96, 208), (16, 48), 64),
            _Inception(512, 160, (112, 224), (24, 64), 64),
            _Inception(512, 128, (128, 256), (24, 64), 64),
            _Inception(512, 112, (144, 288), (32, 64), 64),
            _Inception(528, 256, (160, 320), (32, 128), 128),
            nn.MaxPool2D(3, 2, padding=1))
        self.inc5 = nn.Sequential(
            _Inception(832, 256, (160, 320), (32, 128), 128),
            _Inception(832, 384, (192, 384), (48, 128), 128))
        self.num_classes = num_classes
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.inc5(self.inc4(self.inc3(self.stem(x))))
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)


class InceptionV3(nn.Layer):
    """Compact InceptionV3-style stem + mixed blocks."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()

        def cbr(i, o, k, s=1, p=0):
            return nn.Sequential(nn.Conv2D(i, o, k, stride=s, padding=p,
                                           bias_attr=False),
                                 nn.BatchNorm2D(o), nn.ReLU())
        self.stem = nn.Sequential(
            cbr(3, 32, 3, 2), cbr(32, 32, 3), cbr(32, 64, 3, 1, 1),
            nn.MaxPool2D(3, 2), cbr(64, 80, 1), cbr(80, 192, 3),
            nn.MaxPool2D(3, 2))
        self.mixed = nn.Sequential(
            _Inception(192, 64, (48, 64), (64, 96), 32),
            _Inception(256, 64, (48, 64), (64, 96), 64),
            nn.MaxPool2D(3, 2),
            _Inception(288, 192, (128, 192), (128, 192), 192),
            _Inception(768, 192, (128, 192), (128, 192), 192))
        self.num_classes = num_classes
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.fc = nn.Linear(768, num_classes)

    def forward(self, x):
        x = self.mixed(self.stem(x))
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2

        def dw(i, s):
            return nn.Sequential(
                nn.Conv2D(i, i, 3, stride=s, padding=1, groups=i,
                          bias_attr=False), nn.BatchNorm2D(i))

        def pw(i, o):
            return nn.Sequential(nn.Conv2D(i, o, 1, bias_attr=False),
                                 nn.BatchNorm2D(o), nn.ReLU())
        if stride > 1:
            self.branch1 = nn.Sequential(dw(in_c, stride), pw(in_c, branch_c))
            self.branch2 = nn.Sequential(pw(in_c, branch_c),
                                         dw(branch_c, stride),
                                         pw(branch_c, branch_c))
        else:
            self.branch2 = nn.Sequential(pw(in_c // 2, branch_c),
                                         dw(branch_c, 1),
                                         pw(branch_c, branch_c))
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        if self.stride > 1:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = concat([x1, self.branch2(x2)], axis=1)
        return self.shuffle(out)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        stage_repeats = [4, 8, 4]
        channels = {0.25: [24, 24, 48, 96, 512],
                    0.33: [24, 32, 64, 128, 512],
                    0.5: [24, 48, 96, 192, 1024],
                    1.0: [24, 116, 232, 464, 1024],
                    1.5: [24, 176, 352, 704, 1024],
                    2.0: [24, 244, 488, 976, 2048]}[scale]
        self.stem = nn.Sequential(
            nn.Conv2D(3, channels[0], 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(channels[0]), nn.ReLU(), nn.MaxPool2D(3, 2,
                                                                 padding=1))
        stages = []
        in_c = channels[0]
        for i, reps in enumerate(stage_repeats):
            out_c = channels[i + 1]
            stages.append(_ShuffleUnit(in_c, out_c, 2))
            for _ in range(reps - 1):
                stages.append(_ShuffleUnit(out_c, out_c, 1))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, channels[-1], 1, bias_attr=False),
            nn.BatchNorm2D(channels[-1]), nn.ReLU())
        self.num_classes = num_classes
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        if num_classes > 0:
            self.fc = nn.Linear(channels[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.stem(x)))
        if self.pool is not None:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(1.0, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(0.5, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(264, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(0.33, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(1.0, act="swish", **kwargs)
