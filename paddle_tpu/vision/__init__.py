"""paddle.vision (reference: python/paddle/vision — top-level
re-exports of datasets/models/transforms/ops, like the reference)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401
from .image import (  # noqa: F401
    get_image_backend, image_load, set_image_backend,
)
from .datasets import (  # noqa: F401
    Cifar10, Cifar100, DatasetFolder, FashionMNIST, Flowers, ImageFolder,
    MNIST, VOC2012,
)
from .models import (  # noqa: F401
    AlexNet, DenseNet, GoogLeNet, InceptionV3, LeNet, MobileNetV1,
    MobileNetV2, MobileNetV3Large, MobileNetV3Small, ResNet, ShuffleNetV2,
    SqueezeNet, VGG, alexnet, densenet121, densenet161, densenet169,
    densenet201, densenet264, googlenet, inception_v3, mobilenet_v1,
    mobilenet_v2, mobilenet_v3_large, mobilenet_v3_small, resnet101,
    resnet152, resnet18, resnet34, resnet50, resnext101_32x4d,
    resnext101_64x4d, resnext152_32x4d, resnext152_64x4d, resnext50_32x4d,
    resnext50_64x4d, shufflenet_v2_swish, shufflenet_v2_x0_25,
    shufflenet_v2_x0_33, shufflenet_v2_x0_5, shufflenet_v2_x1_0,
    shufflenet_v2_x1_5, shufflenet_v2_x2_0, squeezenet1_0, squeezenet1_1,
    vgg11, vgg13, vgg16, vgg19, wide_resnet101_2, wide_resnet50_2,
)
from .transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, ColorJitter, Compose,
    ContrastTransform, Grayscale, HueTransform, Normalize, Pad, RandomCrop,
    RandomHorizontalFlip, RandomResizedCrop, RandomRotation,
    RandomVerticalFlip, Resize, SaturationTransform, ToTensor, Transpose,
    adjust_brightness, adjust_contrast, adjust_hue, center_crop, crop,
    hflip, normalize, pad, resize, rotate, to_grayscale, to_tensor, vflip,
)
