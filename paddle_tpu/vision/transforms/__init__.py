# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.vision.transforms (reference: python/paddle/vision/transforms/).

Operate on numpy HWC uint8/float arrays (the DataLoader host path) and on
Tensors where meaningful.
"""
from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

from ...core.tensor import Tensor, to_tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomResizedCrop", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "ColorJitter", "Grayscale",
    "RandomRotation", "to_tensor_fn", "normalize", "resize", "hflip", "vflip",
    "center_crop", "crop",
]


def _as_hwc(img):
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def resize(img, size, interpolation="bilinear"):
    arr = _as_hwc(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            new_h, new_w = size, int(size * w / h)
        else:
            new_h, new_w = int(size * h / w), size
    else:
        new_h, new_w = size
    # simple numpy bilinear/nearest resize
    y = np.linspace(0, arr.shape[0] - 1, new_h)
    x = np.linspace(0, arr.shape[1] - 1, new_w)
    if interpolation == "nearest":
        yi = np.round(y).astype(int)
        xi = np.round(x).astype(int)
        return arr[yi][:, xi]
    y0 = np.floor(y).astype(int)
    x0 = np.floor(x).astype(int)
    y1 = np.minimum(y0 + 1, arr.shape[0] - 1)
    x1 = np.minimum(x0 + 1, arr.shape[1] - 1)
    wy = (y - y0)[:, None, None]
    wx = (x - x0)[None, :, None]
    a = arr.astype(np.float32)
    out = (a[y0][:, x0] * (1 - wy) * (1 - wx) + a[y1][:, x0] * wy * (1 - wx)
           + a[y0][:, x1] * (1 - wy) * wx + a[y1][:, x1] * wy * wx)
    return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _as_hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = (h - th) // 2
    left = (w - tw) // 2
    return crop(arr, top, left, th, tw)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (arr - mean[:, None, None]) / std[:, None, None]
    return (arr - mean) / std


def to_tensor_fn(img, data_format="CHW"):
    arr = _as_hwc(img).astype(np.float32)
    if arr.dtype == np.uint8 or arr.max() > 1.5:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor_fn(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def _apply_image(self, img):
        arr = _as_hwc(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else [self.padding] * 4
            arr = np.pad(arr, ((p[1], p[3]), (p[0], p[2]), (0, 0)))
        h, w = arr.shape[:2]
        th, tw = self.size
        top = random.randint(0, max(h - th, 0))
        left = random.randint(0, max(w - tw, 0))
        return crop(arr, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            aspect = random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target_area * aspect)))
            th = int(round(np.sqrt(target_area / aspect)))
            if 0 < tw <= w and 0 < th <= h:
                top = random.randint(0, h - th)
                left = random.randint(0, w - tw)
                return resize(crop(arr, top, left, th, tw), self.size,
                              self.interpolation)
        return resize(center_crop(arr, min(h, w)), self.size,
                      self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else _as_hwc(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, (list, tuple)) \
            else [padding] * 4
        self.fill = fill

    def _apply_image(self, img):
        p = self.padding
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        return np.pad(_as_hwc(img), ((p[1], p[3]), (p[0], p[2]), (0, 0)),
                      constant_values=self.fill)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        arr = _as_hwc(img).astype(np.float32) * factor
        return np.clip(arr, 0, 255).astype(np.asarray(img).dtype)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        arr = _as_hwc(img).astype(np.float32)
        mean = arr.mean()
        out = (arr - mean) * factor + mean
        return np.clip(out, 0, 255).astype(np.asarray(img).dtype)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        arr = _as_hwc(img).astype(np.float32)
        gray = arr.mean(axis=2, keepdims=True)
        out = (arr - gray) * factor + gray
        return np.clip(out, 0, 255).astype(np.asarray(img).dtype)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        factor = random.uniform(-self.value, self.value)
        return adjust_hue(img, factor)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))

    def _apply_image(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr = _as_hwc(img).astype(np.float32)
        gray = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587
                + arr[..., 2] * 0.114) if arr.shape[2] == 3 else arr[..., 0]
        out = np.repeat(gray[:, :, None], self.num_output_channels, axis=2)
        return out.astype(np.asarray(img).dtype)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees

    def _apply_image(self, img):
        from scipy import ndimage

        angle = random.uniform(*self.degrees)
        arr = _as_hwc(img)
        return ndimage.rotate(arr, angle, reshape=False, order=1)


class RandomErasing(BaseTransform):
    """Randomly erase a rectangle (reference:
    python/paddle/vision/transforms/transforms.py RandomErasing — scale is
    the erased-area fraction range, ratio the aspect-ratio range, value a
    number / per-channel sequence / 'random')."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        if not (isinstance(scale, (tuple, list)) and len(scale) == 2):
            raise ValueError("scale must be a (lo, hi) sequence")
        if not (isinstance(ratio, (tuple, list)) and len(ratio) == 2):
            raise ValueError("ratio must be a (lo, hi) sequence")
        if scale[0] > scale[1] or ratio[0] > ratio[1]:
            raise ValueError("scale/ratio ranges must be (lo, hi)")
        if not 0 <= prob <= 1:
            raise ValueError("prob must be in [0, 1]")
        if isinstance(value, str) and value != "random":
            raise ValueError("value must be a number, a sequence, or "
                             "'random'")
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _get_params(self, img_h, img_w, channels):
        area = img_h * img_w
        import math as _math

        for _ in range(10):
            target = random.uniform(*self.scale) * area
            aspect = _math.exp(random.uniform(_math.log(self.ratio[0]),
                                              _math.log(self.ratio[1])))
            h = int(round(_math.sqrt(target * aspect)))
            w = int(round(_math.sqrt(target / aspect)))
            if 0 < h <= img_h and 0 < w <= img_w:
                top = random.randint(0, img_h - h)
                left = random.randint(0, img_w - w)
                if self.value == "random":
                    v = np.random.standard_normal(
                        (h, w, channels)).astype(np.float32)
                elif isinstance(self.value, (list, tuple)):
                    v = np.asarray(self.value, np.float32).reshape(1, 1, -1)
                else:
                    v = np.float32(self.value)
                return top, left, h, w, v
        return None  # no valid region found; return the image unchanged

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        arr = _as_hwc(img)
        params = self._get_params(arr.shape[0], arr.shape[1], arr.shape[2])
        if params is None:
            return img
        top, left, h, w, v = params
        return erase(img, top, left, h, w, v, inplace=self.inplace)


# ---------------------------------------------------------------------------
# functional API (reference: python/paddle/vision/transforms/functional.py)
# ---------------------------------------------------------------------------

def erase(img, i, j, h, w, v, inplace=False):
    """Fill img[i:i+h, j:j+w] with v (reference functional.erase).

    Accepts HWC ndarrays, CHW Tensors, or anything _as_hwc understands;
    v broadcasts over the erased (h, w, C) region."""
    if isinstance(img, Tensor):  # CHW tensor path, stays a Tensor
        import jax.numpy as jnp

        arr = img._value
        vv = np.asarray(v, np.float32)
        if vv.ndim == 1:          # per-channel fill
            vv = vv.reshape(-1, 1, 1)
        elif vv.ndim == 3:        # (h, w, C) patch -> (C, h, w)
            vv = vv.transpose(2, 0, 1)
        patch = jnp.broadcast_to(jnp.asarray(vv),
                                 (arr.shape[0], h, w)).astype(arr.dtype)
        out = arr.at[:, i:i + h, j:j + w].set(patch)
        if inplace:
            img._value = out
            return img
        return Tensor(out)
    arr = _as_hwc(img)
    out = arr if inplace else arr.copy()
    out[i:i + h, j:j + w] = np.broadcast_to(
        np.asarray(v, out.dtype), (h, w, out.shape[2]))
    return out

def pad(img, padding, fill=0, padding_mode="constant"):
    """Pad an HWC image (functional form of the Pad transform)."""
    p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    left, top, right, bottom = p
    arr = _as_hwc(img)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, ((top, bottom), (left, right), (0, 0)), mode, **kw)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Positive angle = counter-clockwise (matching RandomRotation and the
    PIL convention the reference wraps)."""
    from scipy import ndimage

    arr = _as_hwc(img)
    order = 0 if interpolation == "nearest" else 1
    return ndimage.rotate(arr, angle, axes=(0, 1), reshape=expand,
                          order=order, cval=fill).astype(
                              np.asarray(img).dtype)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)._apply_image(img)


def _img_ceiling(img):
    return 1.0 if np.issubdtype(np.asarray(img).dtype, np.floating) else 255


def adjust_brightness(img, brightness_factor):
    arr = _as_hwc(img).astype(np.float32) * brightness_factor
    return np.clip(arr, 0, _img_ceiling(img)).astype(np.asarray(img).dtype)


def adjust_contrast(img, contrast_factor):
    arr = _as_hwc(img).astype(np.float32)
    # contrast pivots on the grayscale mean (reference semantics)
    gray = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
            ) if arr.shape[2] == 3 else arr[..., 0]
    mean = gray.mean()
    out = (arr - mean) * contrast_factor + mean
    return np.clip(out, 0, _img_ceiling(img)).astype(np.asarray(img).dtype)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) via HSV round trip.

    uint8 inputs are treated as [0, 255]; float inputs as [0, 1] (no
    quantization on the way out)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    raw = _as_hwc(img)
    is_float = np.issubdtype(np.asarray(raw).dtype, np.floating)
    arr = raw.astype(np.float32) / (1.0 if is_float else 255.0)
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    maxc = arr.max(-1)
    minc = arr.min(-1)
    v = maxc
    diff = maxc - minc
    s = np.where(maxc > 0, diff / np.maximum(maxc, 1e-12), 0.0)
    dz = np.where(diff == 0, 1.0, diff)
    rc = (maxc - r) / dz
    gc = (maxc - g) / dz
    bc = (maxc - b) / dz
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = np.where(diff == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    pch = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    r2 = np.choose(i, [v, q, pch, pch, t, v])
    g2 = np.choose(i, [t, v, v, q, pch, pch])
    b2 = np.choose(i, [pch, pch, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1)
    if is_float:
        return np.clip(out, 0.0, 1.0).astype(np.asarray(img).dtype)
    return np.clip(np.round(out * 255.0), 0, 255).astype(
        np.asarray(img).dtype)
