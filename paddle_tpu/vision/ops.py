# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.vision.ops (reference: python/paddle/vision/ops.py): detection
primitives — nms, box coding, roi_align, deform_conv2d (subset)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, in_static_trace
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def box_area(boxes):
    return apply("box_area",
                 lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), _t(boxes))


def box_iou(boxes1, boxes2):
    def _iou(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return apply("box_iou", _iou, _t(boxes1), _t(boxes2))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS — data-dependent output size, so host-side (eager only)."""
    if in_static_trace():
        raise RuntimeError("nms has data-dependent shape; run outside jit")
    b = np.asarray(_t(boxes)._value)
    s = np.asarray(_t(scores)._value) if scores is not None \
        else np.ones(len(b), np.float32)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        w = np.clip(xx2 - xx1, 0, None)
        h = np.clip(yy2 - yy1, 0, None)
        inter = w * h
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear gather (XLA-friendly, static shapes)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _roi(feat, rois):
        # feat [N,C,H,W]; rois [R,4] in x1,y1,x2,y2 (batch 0 assumed per-image
        # via boxes_num split upstream — single image path here)
        C, H, W = feat.shape[1:]
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        bw = (x2 - x1) / ow
        bh = (y2 - y1) / oh
        gy = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * bh[:, None]
        gx = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * bw[:, None]

        # vectorized bilinear gather over rois
        R = rois.shape[0]
        yy = gy[:, :, None]  # [R, oh, 1]
        xx = gx[:, None, :]  # [R, 1, ow]
        yy = jnp.broadcast_to(yy, (R, oh, ow))
        xx = jnp.broadcast_to(xx, (R, oh, ow))
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(yy - y0, 0, 1)
        wx = jnp.clip(xx - x0, 0, 1)
        img = feat[0]  # [C,H,W]
        g = lambda yi, xi: img[:, yi, xi]  # → [C,R,oh,ow] via advanced idx
        out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1_, x0) * wy * (1 - wx)
               + g(y0, x1_) * (1 - wy) * wx + g(y1_, x1_) * wy * wx)
        return jnp.transpose(out, (1, 0, 2, 3))  # [R,C,oh,ow]
    return _per_image_pool(
        _t(x), _t(boxes), boxes_num,
        lambda xi, bi: apply("roi_align", _roi, xi, bi))


def _bin_masks(lo, hi, n_bins, size, quantize):
    """Per-bin membership masks over a length-`size` axis.

    Returns [R, n_bins, size] bool: position p belongs to bin i of roi r.
    quantize=True floors/ceils bin edges (RoIPool semantics)."""
    edges = lo[:, None] + (hi - lo)[:, None] / n_bins * jnp.arange(
        n_bins + 1, dtype=lo.dtype)[None, :]
    start = jnp.floor(edges[:, :-1]) if quantize else edges[:, :-1]
    end = jnp.ceil(edges[:, 1:]) if quantize else edges[:, 1:]
    p = jnp.arange(size, dtype=lo.dtype)[None, None, :]
    return (p >= start[:, :, None]) & (p < jnp.maximum(
        end, start + 1)[:, :, None])


def _per_image_pool(x, boxes, boxes_num, pool_one):
    """Apply a single-image pooling fn per batch image, splitting `boxes`
    by boxes_num (host-concrete in eager mode), and concat row-wise."""
    N = x.shape[0]
    if boxes_num is None:
        if N != 1:
            raise ValueError(
                "batched input needs boxes_num (rois per image); got "
                f"batch={N} with boxes_num=None")
        return pool_one(x, boxes)
    counts = [int(v) for v in np.asarray(_t(boxes_num)._value).reshape(-1)]
    if len(counts) != N:
        raise ValueError(f"boxes_num has {len(counts)} entries for "
                         f"batch {N}")
    outs, start = [], 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        outs.append(pool_one(x[i:i + 1], boxes[start:start + c]))
        start += c
    from ..ops.manipulation import concat

    return outs[0] if len(outs) == 1 else concat(outs, axis=0)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool: exact max over each quantized bin (reference:
    vision/ops.py roi_pool → roi_pool op), computed as masked max
    reductions per output bin — static shapes, XLA-friendly.  Batched
    input routes each roi to its own image via boxes_num."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _roi(feat, rois):
        C, H, W = feat.shape[1:]
        x1 = jnp.floor(rois[:, 0] * spatial_scale)
        y1 = jnp.floor(rois[:, 1] * spatial_scale)
        x2 = jnp.ceil(rois[:, 2] * spatial_scale)
        y2 = jnp.ceil(rois[:, 3] * spatial_scale)
        row_m = _bin_masks(y1, jnp.maximum(y2, y1 + 1), oh, H, True)
        col_m = _bin_masks(x1, jnp.maximum(x2, x1 + 1), ow, W, True)
        img = feat[0]  # [C, H, W]
        neg = jnp.asarray(-3.4e38, img.dtype)
        outs = []
        for i in range(oh):  # static tiny loops over bins
            rm = row_m[:, i][:, None, :, None]  # [R,1,H,1]
            rowred = jnp.max(jnp.where(rm, img[None], neg), axis=2)
            # rowred: [R, C, W]
            cols = []
            for j in range(ow):
                cm = col_m[:, j][:, None, :]
                cols.append(jnp.max(jnp.where(cm, rowred, neg), axis=2))
            outs.append(jnp.stack(cols, axis=-1))  # [R, C, ow]
        return jnp.stack(outs, axis=2)  # [R, C, oh, ow]

    return _per_image_pool(
        _t(x), _t(boxes), boxes_num,
        lambda xi, bi: apply("roi_pool", _roi, xi, bi))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference psroi_pool op): input
    channels C = out_C * oh * ow; bin (i, j) AVERAGES its own channel
    plane over the bin's positions."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _roi(feat, rois):
        C, H, W = feat.shape[1:]
        out_c = C // (oh * ow)
        x1 = rois[:, 0] * spatial_scale
        y1 = rois[:, 1] * spatial_scale
        x2 = rois[:, 2] * spatial_scale
        y2 = rois[:, 3] * spatial_scale
        row_m = _bin_masks(y1, y2, oh, H, True)
        col_m = _bin_masks(x1, x2, ow, W, True)
        planes = feat[0].reshape(out_c, oh, ow, H, W)
        outs = []
        for i in range(oh):
            rm = row_m[:, i].astype(planes.dtype)  # [R, H]
            cols = []
            for j in range(ow):
                cm = col_m[:, j].astype(planes.dtype)  # [R, W]
                mask2 = rm[:, :, None] * cm[:, None, :]  # [R, H, W]
                s = jnp.einsum("chw,rhw->rc", planes[:, i, j], mask2)
                cnt = jnp.maximum(mask2.sum(axis=(1, 2)), 1.0)[:, None]
                cols.append(s / cnt)
            outs.append(jnp.stack(cols, axis=-1))  # [R, out_c, ow]
        return jnp.stack(outs, axis=2)  # [R, out_c, oh, ow]

    return _per_image_pool(
        _t(x), _t(boxes), boxes_num,
        lambda xi, bi: apply("psroi_pool", _roi, xi, bi))


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head predictions into boxes + scores (reference:
    paddle/fluid/operators/detection/yolo_box_op.h semantics)."""
    an = len(anchors) // 2

    def _decode(xv, imgs):
        N, C, H, W = xv.shape
        attrs = 5 + class_num
        if iou_aware:
            # layout (reference yolo_box_util.h GetIoUIndex): an iou
            # channels first, then the an*(5+cls) prediction block
            iou = jax.nn.sigmoid(xv[:, :an].reshape(N, an, H, W))
            p = xv[:, an:].reshape(N, an, attrs, H, W)
        else:
            p = xv.reshape(N, an, attrs, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
        sig = jax.nn.sigmoid
        bias_ = 0.5 * (scale_x_y - 1.0)
        cx = (sig(p[:, :, 0]) * scale_x_y - bias_ + gx) / W
        cy = (sig(p[:, :, 1]) * scale_x_y - bias_ + gy) / H
        bw = jnp.exp(p[:, :, 2]) * aw / (downsample_ratio * W)
        bh = jnp.exp(p[:, :, 3]) * ah / (downsample_ratio * H)
        conf = sig(p[:, :, 4])
        if iou_aware:
            conf = (conf ** (1.0 - iou_aware_factor)
                    * iou ** iou_aware_factor)
        cls = sig(p[:, :, 5:])
        ih = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        iw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * iw
        y1 = (cy - bh / 2) * ih
        x2 = (cx + bw / 2) * iw
        y2 = (cy + bh / 2) * ih
        if clip_bbox:
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
            x2 = jnp.clip(x2, 0, iw - 1)
            y2 = jnp.clip(y2, 0, ih - 1)
        keep = (conf >= conf_thresh).astype(xv.dtype)
        boxes = jnp.stack([x1, y1, x2, y2], axis=2) * keep[:, :, None]
        scores = cls * (conf * keep)[:, :, None]
        boxes = jnp.transpose(boxes, (0, 1, 3, 4, 2)).reshape(
            N, an * H * W, 4)
        scores = jnp.transpose(scores, (0, 1, 3, 4, 2)).reshape(
            N, an * H * W, class_num)
        return boxes, scores

    return apply("yolo_box", _decode, _t(x), _t(img_size))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference:
    paddle/fluid/operators/deformable_conv_op.* / vision/ops.py
    deform_conv2d): bilinear sampling at offset-shifted kernel taps, then
    a grouped matmul — im2col + GEMM, the MXU-friendly formulation.

    offset: [N, 2*dg*kh*kw, Ho, Wo] interleaved (y, x) per tap;
    mask (v2): [N, dg*kh*kw, Ho, Wo]."""
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    dg = deformable_groups

    def _dcn(xv, off, w, *rest, has_mask=False, has_bias=False):
        m = rest[0] if has_mask else None
        b = rest[-1] if has_bias else None
        N, C, H, W = xv.shape
        Cout, Cin_g, kh, kw = w.shape
        Ho, Wo = off.shape[2], off.shape[3]
        off = off.reshape(N, dg, kh * kw, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * sh - ph)[None, :, None]
        base_x = (jnp.arange(Wo) * sw - pw)[None, None, :]
        ky = (jnp.arange(kh) * dh).repeat(kw)
        kx = jnp.tile(jnp.arange(kw) * dw, kh)
        # sampling positions [N, dg, kh*kw, Ho, Wo]
        py = base_y[None, None] + ky[None, None, :, None, None] \
            + off[:, :, :, 0]
        px = base_x[None, None] + kx[None, None, :, None, None] \
            + off[:, :, :, 1]

        y0 = jnp.floor(py)
        x0 = jnp.floor(px)
        wy = py - y0
        wx = px - x0

        cg = C // dg  # channels per deformable group

        def corner(yi, xi):
            valid = ((yi >= 0) & (yi <= H - 1) & (xi >= 0)
                     & (xi <= W - 1)).astype(xv.dtype)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)

            def per_image(img, ycn, xcn, vn):
                # img [dg, cg, H, W]; ycn/xcn [dg, K, Ho, Wo]
                def per_group(g_img, gy, gx, gv):
                    return g_img[:, gy, gx] * gv[None]  # [cg, K, Ho, Wo]

                return jax.vmap(per_group)(img, ycn, xcn, vn)

            imgs = xv.reshape(N, dg, cg, H, W)
            return jax.vmap(per_image)(imgs, yc, xc, valid)

        v00 = corner(y0, x0)
        v01 = corner(y0, x0 + 1)
        v10 = corner(y0 + 1, x0)
        v11 = corner(y0 + 1, x0 + 1)
        wy_ = wy[:, :, None]
        wx_ = wx[:, :, None]
        sampled = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                   + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        # sampled: [N, dg, cg, K, Ho, Wo]
        if m is not None:
            sampled = sampled * m.reshape(N, dg, 1, kh * kw, Ho, Wo)
        cols = sampled.reshape(N, C, kh * kw, Ho, Wo)
        # grouped GEMM: w [Cout, Cin_g, kh*kw]
        wg = w.reshape(groups, Cout // groups, Cin_g, kh * kw)
        colsg = cols.reshape(N, groups, Cin_g, kh * kw, Ho, Wo)
        out = jnp.einsum("gock,ngckhw->ngohw", wg, colsg,
                         preferred_element_type=jnp.float32)
        out = out.reshape(N, Cout, Ho, Wo).astype(xv.dtype)
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    extra = []
    if mask is not None:
        extra.append(_t(mask))
    if bias is not None:
        extra.append(_t(bias))
    return apply("deform_conv2d", _dcn, _t(x), _t(offset), _t(weight),
                 *extra, has_mask=mask is not None,
                 has_bias=bias is not None)


def read_file(filename, name=None):
    """File bytes as a uint8 tensor (reference: vision/ops.py read_file)."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode JPEG bytes to [C, H, W] uint8 (reference decode_jpeg, host
    side).  Uses Pillow when available."""
    try:
        import io

        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("decode_jpeg requires Pillow on the host") from e
    raw = bytes(np.asarray(_t(x)._value, np.uint8).tobytes())
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


# ------------------------------------------------------- layer wrappers
def _to_2tuple(v):
    return (v, v) if isinstance(v, int) else tuple(v)


# nn imports vision transforms indirectly, so the Layer subclasses are
# defined ONCE on first use (stable types: isinstance and
# type(a) is type(b) behave normally) via this memoized factory.
_layer_classes = {}


def _get_layer_class(name):
    if name in _layer_classes:
        return _layer_classes[name]
    from .. import nn
    from ..nn import initializer as I

    class _DeformConv2D(nn.Layer):
        """Layer over deform_conv2d (reference vision/ops.py DeformConv2D)."""

        def __init__(self, in_channels, out_channels, kernel_size,
                     stride=1, padding=0, dilation=1, deformable_groups=1,
                     groups=1, weight_attr=None, bias_attr=None):
            super().__init__()
            kh, kw = _to_2tuple(kernel_size)
            self._attrs = dict(stride=stride, padding=padding,
                               dilation=dilation,
                               deformable_groups=deformable_groups,
                               groups=groups)
            self.weight = self.create_parameter(
                [out_channels, in_channels // groups, kh, kw],
                attr=weight_attr)
            self.bias = None if bias_attr is False else \
                self.create_parameter([out_channels], attr=bias_attr,
                                      default_initializer=I.Constant(0.0))

        def forward(self, x, offset, mask=None):
            return deform_conv2d(x, offset, self.weight, self.bias,
                                 mask=mask, **self._attrs)

    def make_pool(pool_fn, cls_name):
        class _Pool(nn.Layer):
            def __init__(self, output_size, spatial_scale=1.0):
                super().__init__()
                self._output_size = output_size
                self._spatial_scale = spatial_scale

            def forward(self, x, boxes, boxes_num=None):
                return pool_fn(x, boxes, boxes_num, self._output_size,
                               self._spatial_scale)

        _Pool.__name__ = _Pool.__qualname__ = cls_name
        return _Pool

    _layer_classes.update({
        "DeformConv2D": _DeformConv2D,
        "RoIAlign": make_pool(roi_align, "RoIAlign"),
        "RoIPool": make_pool(roi_pool, "RoIPool"),
        "PSRoIPool": make_pool(psroi_pool, "PSRoIPool"),
    })
    return _layer_classes[name]


class _LazyLayer:
    """Callable + isinstance-able proxy for a lazily-defined Layer class."""

    def __init__(self, name):
        self._name = name
        self.__name__ = name

    def __call__(self, *args, **kwargs):
        return _get_layer_class(self._name)(*args, **kwargs)

    def __instancecheck__(self, obj):
        return isinstance(obj, _get_layer_class(self._name))


DeformConv2D = _LazyLayer("DeformConv2D")
RoIAlign = _LazyLayer("RoIAlign")
RoIPool = _LazyLayer("RoIPool")
PSRoIPool = _LazyLayer("PSRoIPool")

_UNSET = object()


def ConvNormActivation(in_channels, out_channels, kernel_size=3, stride=1,
                       padding=None, groups=1, norm_layer=_UNSET,
                       activation_layer=_UNSET, dilation=1, bias=None):
    """Conv2D + Norm + Activation block (reference: vision/ops.py
    ConvNormActivation).  Pass norm_layer=None / activation_layer=None to
    genuinely omit that stage (the defaults are BatchNorm2D / ReLU)."""
    from .. import nn

    if padding is None:
        padding = (kernel_size - 1) // 2 * dilation
    if norm_layer is _UNSET:
        norm_layer = nn.BatchNorm2D
    if activation_layer is _UNSET:
        activation_layer = nn.ReLU
    if bias is None:
        bias = norm_layer is None
    layers = [nn.Conv2D(in_channels, out_channels, kernel_size, stride,
                        padding, dilation=dilation, groups=groups,
                        bias_attr=None if bias else False)]
    if norm_layer is not None:
        layers.append(norm_layer(out_channels))
    if activation_layer is not None:
        layers.append(activation_layer())
    return nn.Sequential(*layers)


# ------------------------------------------------------------------ yolo
_BBOX_CLIP = float(np.log(1000.0 / 16.0))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference:
    python/paddle/vision/ops.py:43 yolo_loss over
    phi/kernels/cpu/yolov3_loss_kernel.cc Yolov3LossKernel).

    TPU-native: fully vectorized jnp — per-cell ignore masks from a
    broadcast IoU against all gt boxes, per-gt anchor matching by argmax,
    and a lax.scan over the (static) gt slots reproducing the kernel's
    sequential obj-mask overwrite semantics.  Differentiable w.r.t. x by
    construction (the reference ships a handwritten grad kernel).
    x: [N, S*(5+C), H, W]; gt_box: [N, B, 4] (cx, cy, w, h in [0, 1]);
    gt_label: [N, B] int; returns loss [N]."""
    anchors = [int(a) for a in anchors]
    anchor_mask = [int(m) for m in anchor_mask]
    S = len(anchor_mask)
    C = int(class_num)
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    def _sce(logit, label):
        # sigmoid cross entropy, the kernel's numerically-stable form
        return (jnp.maximum(logit, 0.0) - logit * label
                + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    def _iou_cwh(b1, b2):
        # boxes as (cx, cy, w, h); ... broadcastable
        lo = jnp.maximum(b1[..., :2] - b1[..., 2:] / 2,
                         b2[..., :2] - b2[..., 2:] / 2)
        hi = jnp.minimum(b1[..., :2] + b1[..., 2:] / 2,
                         b2[..., :2] + b2[..., 2:] / 2)
        wh = jnp.clip(hi - lo, 0.0, None)
        inter = wh[..., 0] * wh[..., 1]
        union = (b1[..., 2] * b1[..., 3] + b2[..., 2] * b2[..., 3] - inter)
        return inter / jnp.where(union > 0, union, 1.0)

    def _fn(xv, gtb, gtl, *rest):
        gts = rest[0] if rest else None
        N, _, H, W = xv.shape
        B = gtb.shape[1]
        input_size = downsample_ratio * H
        xr = xv.reshape(N, S, 5 + C, H, W)
        anc = jnp.asarray(anchors, xv.dtype).reshape(-1, 2)  # [A, 2]
        anc_m = anc[jnp.asarray(anchor_mask)]                # [S, 2]

        if use_label_smooth:
            sm = min(1.0 / C, 1.0 / 40.0)
            pos, neg = 1.0 - sm, sm
        else:
            pos, neg = 1.0, 0.0
        score = gts if gts is not None else jnp.ones((N, B), xv.dtype)
        valid = (gtb[..., 2] > 1e-6) & (gtb[..., 3] > 1e-6)   # [N, B]

        # ---- per-cell decoded boxes & ignore mask (no grad: the kernel
        # computes the mask as data, not through autodiff)
        xd = jax.lax.stop_gradient(xr)
        gy, gx = jnp.meshgrid(jnp.arange(H), jnp.arange(W), indexing="ij")
        px = (gx[None, None] + jax.nn.sigmoid(xd[:, :, 0]) * scale
              + bias) / W
        py = (gy[None, None] + jax.nn.sigmoid(xd[:, :, 1]) * scale
              + bias) / H
        pw = jnp.exp(xd[:, :, 2]) * anc_m[None, :, 0, None, None] \
            / input_size
        ph = jnp.exp(xd[:, :, 3]) * anc_m[None, :, 1, None, None] \
            / input_size
        pred = jnp.stack([px, py, pw, ph], -1)          # [N, S, H, W, 4]
        ious = _iou_cwh(pred[:, :, :, :, None, :],
                        gtb[:, None, None, None, :, :])  # [N,S,H,W,B]
        ious = jnp.where(valid[:, None, None, None, :], ious, 0.0)
        best_iou = ious.max(-1)                          # [N, S, H, W]
        obj_mask0 = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)

        # ---- per-gt anchor matching (vs ALL anchors, centered boxes)
        an_wh = anc / input_size                         # [A, 2]
        zeros2 = jnp.zeros_like(an_wh)
        an_boxes = jnp.concatenate([zeros2, an_wh], -1)  # [A, 4]
        gt_shift = gtb.at[..., :2].set(0.0)              # [N, B, 4]
        an_iou = _iou_cwh(gt_shift[:, :, None, :],
                          an_boxes[None, None, :, :])    # [N, B, A]
        best_n = jnp.argmax(an_iou, -1)                  # [N, B]
        mask_lut = -np.ones(len(anchors) // 2, np.int32)
        for mi, a in enumerate(anchor_mask):
            mask_lut[a] = mi
        mask_idx = jnp.asarray(mask_lut)[best_n]         # [N, B]
        gi = jnp.clip((gtb[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gtb[..., 1] * H).astype(jnp.int32), 0, H - 1)
        use = valid & (mask_idx >= 0)

        # ---- scan over gt slots: location/class losses + obj overwrite
        nidx = jnp.arange(N)

        def per_gt(carry, t):
            loss, obj = carry
            gt_t = gtb[:, t]                              # [N, 4]
            mi = jnp.clip(mask_idx[:, t], 0, S - 1)
            gi_t, gj_t = gi[:, t], gj[:, t]
            sc = score[:, t] * use[:, t].astype(xv.dtype)
            cell = xr[nidx, mi, :, gj_t, gi_t]            # [N, 5+C]
            tx = gt_t[:, 0] * W - gi_t
            ty = gt_t[:, 1] * H - gj_t
            tw = jnp.log(jnp.where(use[:, t],
                                   gt_t[:, 2] * input_size
                                   / anc[jnp.clip(best_n[:, t], 0,
                                                  anc.shape[0] - 1), 0],
                                   1.0))
            th = jnp.log(jnp.where(use[:, t],
                                   gt_t[:, 3] * input_size
                                   / anc[jnp.clip(best_n[:, t], 0,
                                                  anc.shape[0] - 1), 1],
                                   1.0))
            wbox = (2.0 - gt_t[:, 2] * gt_t[:, 3]) * sc
            l_loc = (_sce(cell[:, 0], tx) + _sce(cell[:, 1], ty)
                     + jnp.abs(cell[:, 2] - tw)
                     + jnp.abs(cell[:, 3] - th)) * wbox
            labels1h = jnp.where(
                jax.nn.one_hot(gtl[:, t], C) > 0, pos, neg)
            l_cls = (_sce(cell[:, 5:], labels1h).sum(-1)) * sc
            loss = loss + l_loc + l_cls
            obj = obj.at[nidx, mi, gj_t, gi_t].set(
                jnp.where(use[:, t], sc, obj[nidx, mi, gj_t, gi_t]))
            return (loss, obj), None

        (loss, obj_mask), _ = jax.lax.scan(
            per_gt, (jnp.zeros((N,), xv.dtype), obj_mask0), jnp.arange(B))

        # ---- objectness loss over every cell
        obj_logit = xr[:, :, 4]                           # [N, S, H, W]
        l_pos = _sce(obj_logit, 1.0) * obj_mask
        l_neg = _sce(obj_logit, 0.0)
        l_obj = jnp.where(obj_mask > 1e-5, l_pos,
                          jnp.where(obj_mask > -0.5, l_neg, 0.0))
        return loss + l_obj.sum((1, 2, 3))

    args = [_t(x), _t(gt_box), _t(gt_label)]
    if gt_score is not None:
        args.append(_t(gt_score))
    return apply("yolo_loss", _fn, *args)


# ------------------------------------------------- proposal generation
def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """Faster-RCNN RPN proposals (reference:
    fluid/layers/detection.py:2908 generate_proposals over
    fluid/operators/detection/generate_proposals_v2_op.cc
    ProposalForOneImage: top-k -> BoxCoder decode -> clip -> min-size
    filter -> NMS -> top post_nms).  Data-dependent output sizes: host-
    side op (eager only), like nms."""
    if in_static_trace():
        raise RuntimeError(
            "generate_proposals has data-dependent shape; run outside jit")
    sc = np.asarray(_t(scores)._value)       # [N, A, H, W]
    bd = np.asarray(_t(bbox_deltas)._value)  # [N, 4A, H, W]
    ims = np.asarray(_t(img_size)._value)    # [N, 2] (h, w)
    anc = np.asarray(_t(anchors)._value).reshape(-1, 4)
    var = np.asarray(_t(variances)._value).reshape(-1, 4)
    N, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, rois_num = [], [], []
    for i in range(N):
        s = sc[i].transpose(1, 2, 0).reshape(-1)          # [(H W A)]
        d = bd[i].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        k = len(s) if pre_nms_top_n <= 0 else min(pre_nms_top_n, len(s))
        order = np.argsort(-s)[:k]
        s_sel, d_sel = s[order], d[order]
        a_sel, v_sel = anc[order], var[order]

        aw = a_sel[:, 2] - a_sel[:, 0] + off
        ah = a_sel[:, 3] - a_sel[:, 1] + off
        acx = a_sel[:, 0] + 0.5 * aw
        acy = a_sel[:, 1] + 0.5 * ah
        cx = v_sel[:, 0] * d_sel[:, 0] * aw + acx
        cy = v_sel[:, 1] * d_sel[:, 1] * ah + acy
        bw = np.exp(np.minimum(v_sel[:, 2] * d_sel[:, 2], _BBOX_CLIP)) * aw
        bh = np.exp(np.minimum(v_sel[:, 3] * d_sel[:, 3], _BBOX_CLIP)) * ah
        props = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - off, cy + bh / 2 - off], 1)
        imh, imw = float(ims[i][0]), float(ims[i][1])
        props[:, 0] = np.clip(props[:, 0], 0, imw - off)
        props[:, 1] = np.clip(props[:, 1], 0, imh - off)
        props[:, 2] = np.clip(props[:, 2], 0, imw - off)
        props[:, 3] = np.clip(props[:, 3], 0, imh - off)

        ms = max(float(min_size), 1.0)
        ws = props[:, 2] - props[:, 0] + off
        hs = props[:, 3] - props[:, 1] + off
        keep = (ws >= ms) & (hs >= ms)
        if pixel_offset:
            keep &= (props[:, 0] + ws / 2 <= imw) & \
                    (props[:, 1] + hs / 2 <= imh)
        props, s_keep = props[keep], s_sel[keep]
        if len(props) == 0:
            props = np.zeros((1, 4), sc.dtype)
            s_keep = np.zeros((1,), sc.dtype)
        elif nms_thresh > 0:
            ki = np.asarray(nms(Tensor(jnp.asarray(props)),
                                iou_threshold=nms_thresh,
                                scores=Tensor(jnp.asarray(s_keep)))
                            ._value)
            if post_nms_top_n > 0:
                ki = ki[:post_nms_top_n]
            props, s_keep = props[ki], s_keep[ki]
        all_rois.append(props)
        all_probs.append(s_keep[:, None])
        rois_num.append(len(props))

    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, 0)))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(rois_num,
                                                          np.int32)))
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Scatter RoIs to FPN levels by box scale (reference:
    fluid/layers/detection.py:3687 over
    operators/detection/distribute_fpn_proposals_op.h: level =
    floor(log2(sqrt(area)/refer_scale + 1e-6) + refer_level), clipped).
    Returns (multi_rois list, restore_ind [, rois_num_per_level])."""
    if in_static_trace():
        raise RuntimeError("distribute_fpn_proposals has data-dependent "
                           "shape; run outside jit")
    rois = np.asarray(_t(fpn_rois)._value)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.clip(w * h, 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6) + refer_level)
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)

    multi_rois, nums, order = [], [], []
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        multi_rois.append(Tensor(jnp.asarray(
            rois[idx] if len(idx) else np.zeros((0, 4), rois.dtype))))
        nums.append(len(idx))
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    # restore_ind[j] = position of original roi j in the concatenated
    # level-major output (the reference's RestoreIndex)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    restore_ind = Tensor(jnp.asarray(restore[:, None].astype(np.int32)))
    if rois_num is not None:
        return multi_rois, restore_ind, Tensor(
            jnp.asarray(np.asarray(nums, np.int32)))
    return multi_rois, restore_ind
