"""paddle.vision.ops (reference: python/paddle/vision/ops.py): detection
primitives — nms, box coding, roi_align, deform_conv2d (subset)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, in_static_trace
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def box_area(boxes):
    return apply("box_area",
                 lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), _t(boxes))


def box_iou(boxes1, boxes2):
    def _iou(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return apply("box_iou", _iou, _t(boxes1), _t(boxes2))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS — data-dependent output size, so host-side (eager only)."""
    if in_static_trace():
        raise RuntimeError("nms has data-dependent shape; run outside jit")
    b = np.asarray(_t(boxes)._value)
    s = np.asarray(_t(scores)._value) if scores is not None \
        else np.ones(len(b), np.float32)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        w = np.clip(xx2 - xx1, 0, None)
        h = np.clip(yy2 - yy1, 0, None)
        inter = w * h
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear gather (XLA-friendly, static shapes)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _roi(feat, rois):
        # feat [N,C,H,W]; rois [R,4] in x1,y1,x2,y2 (batch 0 assumed per-image
        # via boxes_num split upstream — single image path here)
        C, H, W = feat.shape[1:]
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        bw = (x2 - x1) / ow
        bh = (y2 - y1) / oh
        gy = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * bh[:, None]
        gx = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * bw[:, None]

        # vectorized bilinear gather over rois
        R = rois.shape[0]
        yy = gy[:, :, None]  # [R, oh, 1]
        xx = gx[:, None, :]  # [R, 1, ow]
        yy = jnp.broadcast_to(yy, (R, oh, ow))
        xx = jnp.broadcast_to(xx, (R, oh, ow))
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(yy - y0, 0, 1)
        wx = jnp.clip(xx - x0, 0, 1)
        img = feat[0]  # [C,H,W]
        g = lambda yi, xi: img[:, yi, xi]  # → [C,R,oh,ow] via advanced idx
        out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1_, x0) * wy * (1 - wx)
               + g(y0, x1_) * (1 - wy) * wx + g(y1_, x1_) * wy * wx)
        return jnp.transpose(out, (1, 0, 2, 3))  # [R,C,oh,ow]
    return apply("roi_align", _roi, _t(x), _t(boxes))


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    raise NotImplementedError("yolo_box: planned detection-suite op")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    raise NotImplementedError("deform_conv2d: planned detection-suite op")
