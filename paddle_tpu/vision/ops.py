"""paddle.vision.ops (reference: python/paddle/vision/ops.py): detection
primitives — nms, box coding, roi_align, deform_conv2d (subset)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, in_static_trace
from ..core.tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def box_area(boxes):
    return apply("box_area",
                 lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), _t(boxes))


def box_iou(boxes1, boxes2):
    def _iou(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return apply("box_iou", _iou, _t(boxes1), _t(boxes2))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS — data-dependent output size, so host-side (eager only)."""
    if in_static_trace():
        raise RuntimeError("nms has data-dependent shape; run outside jit")
    b = np.asarray(_t(boxes)._value)
    s = np.asarray(_t(scores)._value) if scores is not None \
        else np.ones(len(b), np.float32)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        w = np.clip(xx2 - xx1, 0, None)
        h = np.clip(yy2 - yy1, 0, None)
        inter = w * h
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear gather (XLA-friendly, static shapes)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _roi(feat, rois):
        # feat [N,C,H,W]; rois [R,4] in x1,y1,x2,y2 (batch 0 assumed per-image
        # via boxes_num split upstream — single image path here)
        C, H, W = feat.shape[1:]
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        bw = (x2 - x1) / ow
        bh = (y2 - y1) / oh
        gy = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * bh[:, None]
        gx = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * bw[:, None]

        # vectorized bilinear gather over rois
        R = rois.shape[0]
        yy = gy[:, :, None]  # [R, oh, 1]
        xx = gx[:, None, :]  # [R, 1, ow]
        yy = jnp.broadcast_to(yy, (R, oh, ow))
        xx = jnp.broadcast_to(xx, (R, oh, ow))
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(yy - y0, 0, 1)
        wx = jnp.clip(xx - x0, 0, 1)
        img = feat[0]  # [C,H,W]
        g = lambda yi, xi: img[:, yi, xi]  # → [C,R,oh,ow] via advanced idx
        out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1_, x0) * wy * (1 - wx)
               + g(y0, x1_) * (1 - wy) * wx + g(y1_, x1_) * wy * wx)
        return jnp.transpose(out, (1, 0, 2, 3))  # [R,C,oh,ow]
    return _per_image_pool(
        _t(x), _t(boxes), boxes_num,
        lambda xi, bi: apply("roi_align", _roi, xi, bi))


def _bin_masks(lo, hi, n_bins, size, quantize):
    """Per-bin membership masks over a length-`size` axis.

    Returns [R, n_bins, size] bool: position p belongs to bin i of roi r.
    quantize=True floors/ceils bin edges (RoIPool semantics)."""
    edges = lo[:, None] + (hi - lo)[:, None] / n_bins * jnp.arange(
        n_bins + 1, dtype=lo.dtype)[None, :]
    start = jnp.floor(edges[:, :-1]) if quantize else edges[:, :-1]
    end = jnp.ceil(edges[:, 1:]) if quantize else edges[:, 1:]
    p = jnp.arange(size, dtype=lo.dtype)[None, None, :]
    return (p >= start[:, :, None]) & (p < jnp.maximum(
        end, start + 1)[:, :, None])


def _per_image_pool(x, boxes, boxes_num, pool_one):
    """Apply a single-image pooling fn per batch image, splitting `boxes`
    by boxes_num (host-concrete in eager mode), and concat row-wise."""
    N = x.shape[0]
    if boxes_num is None:
        if N != 1:
            raise ValueError(
                "batched input needs boxes_num (rois per image); got "
                f"batch={N} with boxes_num=None")
        return pool_one(x, boxes)
    counts = [int(v) for v in np.asarray(_t(boxes_num)._value).reshape(-1)]
    if len(counts) != N:
        raise ValueError(f"boxes_num has {len(counts)} entries for "
                         f"batch {N}")
    outs, start = [], 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        outs.append(pool_one(x[i:i + 1], boxes[start:start + c]))
        start += c
    from ..ops.manipulation import concat

    return outs[0] if len(outs) == 1 else concat(outs, axis=0)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool: exact max over each quantized bin (reference:
    vision/ops.py roi_pool → roi_pool op), computed as masked max
    reductions per output bin — static shapes, XLA-friendly.  Batched
    input routes each roi to its own image via boxes_num."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _roi(feat, rois):
        C, H, W = feat.shape[1:]
        x1 = jnp.floor(rois[:, 0] * spatial_scale)
        y1 = jnp.floor(rois[:, 1] * spatial_scale)
        x2 = jnp.ceil(rois[:, 2] * spatial_scale)
        y2 = jnp.ceil(rois[:, 3] * spatial_scale)
        row_m = _bin_masks(y1, jnp.maximum(y2, y1 + 1), oh, H, True)
        col_m = _bin_masks(x1, jnp.maximum(x2, x1 + 1), ow, W, True)
        img = feat[0]  # [C, H, W]
        neg = jnp.asarray(-3.4e38, img.dtype)
        outs = []
        for i in range(oh):  # static tiny loops over bins
            rm = row_m[:, i][:, None, :, None]  # [R,1,H,1]
            rowred = jnp.max(jnp.where(rm, img[None], neg), axis=2)
            # rowred: [R, C, W]
            cols = []
            for j in range(ow):
                cm = col_m[:, j][:, None, :]
                cols.append(jnp.max(jnp.where(cm, rowred, neg), axis=2))
            outs.append(jnp.stack(cols, axis=-1))  # [R, C, ow]
        return jnp.stack(outs, axis=2)  # [R, C, oh, ow]

    return _per_image_pool(
        _t(x), _t(boxes), boxes_num,
        lambda xi, bi: apply("roi_pool", _roi, xi, bi))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference psroi_pool op): input
    channels C = out_C * oh * ow; bin (i, j) AVERAGES its own channel
    plane over the bin's positions."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def _roi(feat, rois):
        C, H, W = feat.shape[1:]
        out_c = C // (oh * ow)
        x1 = rois[:, 0] * spatial_scale
        y1 = rois[:, 1] * spatial_scale
        x2 = rois[:, 2] * spatial_scale
        y2 = rois[:, 3] * spatial_scale
        row_m = _bin_masks(y1, y2, oh, H, True)
        col_m = _bin_masks(x1, x2, ow, W, True)
        planes = feat[0].reshape(out_c, oh, ow, H, W)
        outs = []
        for i in range(oh):
            rm = row_m[:, i].astype(planes.dtype)  # [R, H]
            cols = []
            for j in range(ow):
                cm = col_m[:, j].astype(planes.dtype)  # [R, W]
                mask2 = rm[:, :, None] * cm[:, None, :]  # [R, H, W]
                s = jnp.einsum("chw,rhw->rc", planes[:, i, j], mask2)
                cnt = jnp.maximum(mask2.sum(axis=(1, 2)), 1.0)[:, None]
                cols.append(s / cnt)
            outs.append(jnp.stack(cols, axis=-1))  # [R, out_c, ow]
        return jnp.stack(outs, axis=2)  # [R, out_c, oh, ow]

    return _per_image_pool(
        _t(x), _t(boxes), boxes_num,
        lambda xi, bi: apply("psroi_pool", _roi, xi, bi))


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head predictions into boxes + scores (reference:
    paddle/fluid/operators/detection/yolo_box_op.h semantics)."""
    an = len(anchors) // 2

    def _decode(xv, imgs):
        N, C, H, W = xv.shape
        attrs = 5 + class_num
        if iou_aware:
            # layout (reference yolo_box_util.h GetIoUIndex): an iou
            # channels first, then the an*(5+cls) prediction block
            iou = jax.nn.sigmoid(xv[:, :an].reshape(N, an, H, W))
            p = xv[:, an:].reshape(N, an, attrs, H, W)
        else:
            p = xv.reshape(N, an, attrs, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
        sig = jax.nn.sigmoid
        bias_ = 0.5 * (scale_x_y - 1.0)
        cx = (sig(p[:, :, 0]) * scale_x_y - bias_ + gx) / W
        cy = (sig(p[:, :, 1]) * scale_x_y - bias_ + gy) / H
        bw = jnp.exp(p[:, :, 2]) * aw / (downsample_ratio * W)
        bh = jnp.exp(p[:, :, 3]) * ah / (downsample_ratio * H)
        conf = sig(p[:, :, 4])
        if iou_aware:
            conf = (conf ** (1.0 - iou_aware_factor)
                    * iou ** iou_aware_factor)
        cls = sig(p[:, :, 5:])
        ih = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        iw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * iw
        y1 = (cy - bh / 2) * ih
        x2 = (cx + bw / 2) * iw
        y2 = (cy + bh / 2) * ih
        if clip_bbox:
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
            x2 = jnp.clip(x2, 0, iw - 1)
            y2 = jnp.clip(y2, 0, ih - 1)
        keep = (conf >= conf_thresh).astype(xv.dtype)
        boxes = jnp.stack([x1, y1, x2, y2], axis=2) * keep[:, :, None]
        scores = cls * (conf * keep)[:, :, None]
        boxes = jnp.transpose(boxes, (0, 1, 3, 4, 2)).reshape(
            N, an * H * W, 4)
        scores = jnp.transpose(scores, (0, 1, 3, 4, 2)).reshape(
            N, an * H * W, class_num)
        return boxes, scores

    return apply("yolo_box", _decode, _t(x), _t(img_size))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference:
    paddle/fluid/operators/deformable_conv_op.* / vision/ops.py
    deform_conv2d): bilinear sampling at offset-shifted kernel taps, then
    a grouped matmul — im2col + GEMM, the MXU-friendly formulation.

    offset: [N, 2*dg*kh*kw, Ho, Wo] interleaved (y, x) per tap;
    mask (v2): [N, dg*kh*kw, Ho, Wo]."""
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    dg = deformable_groups

    def _dcn(xv, off, w, *rest, has_mask=False, has_bias=False):
        m = rest[0] if has_mask else None
        b = rest[-1] if has_bias else None
        N, C, H, W = xv.shape
        Cout, Cin_g, kh, kw = w.shape
        Ho, Wo = off.shape[2], off.shape[3]
        off = off.reshape(N, dg, kh * kw, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * sh - ph)[None, :, None]
        base_x = (jnp.arange(Wo) * sw - pw)[None, None, :]
        ky = (jnp.arange(kh) * dh).repeat(kw)
        kx = jnp.tile(jnp.arange(kw) * dw, kh)
        # sampling positions [N, dg, kh*kw, Ho, Wo]
        py = base_y[None, None] + ky[None, None, :, None, None] \
            + off[:, :, :, 0]
        px = base_x[None, None] + kx[None, None, :, None, None] \
            + off[:, :, :, 1]

        y0 = jnp.floor(py)
        x0 = jnp.floor(px)
        wy = py - y0
        wx = px - x0

        cg = C // dg  # channels per deformable group

        def corner(yi, xi):
            valid = ((yi >= 0) & (yi <= H - 1) & (xi >= 0)
                     & (xi <= W - 1)).astype(xv.dtype)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)

            def per_image(img, ycn, xcn, vn):
                # img [dg, cg, H, W]; ycn/xcn [dg, K, Ho, Wo]
                def per_group(g_img, gy, gx, gv):
                    return g_img[:, gy, gx] * gv[None]  # [cg, K, Ho, Wo]

                return jax.vmap(per_group)(img, ycn, xcn, vn)

            imgs = xv.reshape(N, dg, cg, H, W)
            return jax.vmap(per_image)(imgs, yc, xc, valid)

        v00 = corner(y0, x0)
        v01 = corner(y0, x0 + 1)
        v10 = corner(y0 + 1, x0)
        v11 = corner(y0 + 1, x0 + 1)
        wy_ = wy[:, :, None]
        wx_ = wx[:, :, None]
        sampled = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                   + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        # sampled: [N, dg, cg, K, Ho, Wo]
        if m is not None:
            sampled = sampled * m.reshape(N, dg, 1, kh * kw, Ho, Wo)
        cols = sampled.reshape(N, C, kh * kw, Ho, Wo)
        # grouped GEMM: w [Cout, Cin_g, kh*kw]
        wg = w.reshape(groups, Cout // groups, Cin_g, kh * kw)
        colsg = cols.reshape(N, groups, Cin_g, kh * kw, Ho, Wo)
        out = jnp.einsum("gock,ngckhw->ngohw", wg, colsg,
                         preferred_element_type=jnp.float32)
        out = out.reshape(N, Cout, Ho, Wo).astype(xv.dtype)
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    extra = []
    if mask is not None:
        extra.append(_t(mask))
    if bias is not None:
        extra.append(_t(bias))
    return apply("deform_conv2d", _dcn, _t(x), _t(offset), _t(weight),
                 *extra, has_mask=mask is not None,
                 has_bias=bias is not None)


def read_file(filename, name=None):
    """File bytes as a uint8 tensor (reference: vision/ops.py read_file)."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode JPEG bytes to [C, H, W] uint8 (reference decode_jpeg, host
    side).  Uses Pillow when available."""
    try:
        import io

        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("decode_jpeg requires Pillow on the host") from e
    raw = bytes(np.asarray(_t(x)._value, np.uint8).tobytes())
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


# ------------------------------------------------------- layer wrappers
def _to_2tuple(v):
    return (v, v) if isinstance(v, int) else tuple(v)


# nn imports vision transforms indirectly, so the Layer subclasses are
# defined ONCE on first use (stable types: isinstance and
# type(a) is type(b) behave normally) via this memoized factory.
_layer_classes = {}


def _get_layer_class(name):
    if name in _layer_classes:
        return _layer_classes[name]
    from .. import nn
    from ..nn import initializer as I

    class _DeformConv2D(nn.Layer):
        """Layer over deform_conv2d (reference vision/ops.py DeformConv2D)."""

        def __init__(self, in_channels, out_channels, kernel_size,
                     stride=1, padding=0, dilation=1, deformable_groups=1,
                     groups=1, weight_attr=None, bias_attr=None):
            super().__init__()
            kh, kw = _to_2tuple(kernel_size)
            self._attrs = dict(stride=stride, padding=padding,
                               dilation=dilation,
                               deformable_groups=deformable_groups,
                               groups=groups)
            self.weight = self.create_parameter(
                [out_channels, in_channels // groups, kh, kw],
                attr=weight_attr)
            self.bias = None if bias_attr is False else \
                self.create_parameter([out_channels], attr=bias_attr,
                                      default_initializer=I.Constant(0.0))

        def forward(self, x, offset, mask=None):
            return deform_conv2d(x, offset, self.weight, self.bias,
                                 mask=mask, **self._attrs)

    def make_pool(pool_fn, cls_name):
        class _Pool(nn.Layer):
            def __init__(self, output_size, spatial_scale=1.0):
                super().__init__()
                self._output_size = output_size
                self._spatial_scale = spatial_scale

            def forward(self, x, boxes, boxes_num=None):
                return pool_fn(x, boxes, boxes_num, self._output_size,
                               self._spatial_scale)

        _Pool.__name__ = _Pool.__qualname__ = cls_name
        return _Pool

    _layer_classes.update({
        "DeformConv2D": _DeformConv2D,
        "RoIAlign": make_pool(roi_align, "RoIAlign"),
        "RoIPool": make_pool(roi_pool, "RoIPool"),
        "PSRoIPool": make_pool(psroi_pool, "PSRoIPool"),
    })
    return _layer_classes[name]


class _LazyLayer:
    """Callable + isinstance-able proxy for a lazily-defined Layer class."""

    def __init__(self, name):
        self._name = name
        self.__name__ = name

    def __call__(self, *args, **kwargs):
        return _get_layer_class(self._name)(*args, **kwargs)

    def __instancecheck__(self, obj):
        return isinstance(obj, _get_layer_class(self._name))


DeformConv2D = _LazyLayer("DeformConv2D")
RoIAlign = _LazyLayer("RoIAlign")
RoIPool = _LazyLayer("RoIPool")
PSRoIPool = _LazyLayer("PSRoIPool")

_UNSET = object()


def ConvNormActivation(in_channels, out_channels, kernel_size=3, stride=1,
                       padding=None, groups=1, norm_layer=_UNSET,
                       activation_layer=_UNSET, dilation=1, bias=None):
    """Conv2D + Norm + Activation block (reference: vision/ops.py
    ConvNormActivation).  Pass norm_layer=None / activation_layer=None to
    genuinely omit that stage (the defaults are BatchNorm2D / ReLU)."""
    from .. import nn

    if padding is None:
        padding = (kernel_size - 1) // 2 * dilation
    if norm_layer is _UNSET:
        norm_layer = nn.BatchNorm2D
    if activation_layer is _UNSET:
        activation_layer = nn.ReLU
    if bias is None:
        bias = norm_layer is None
    layers = [nn.Conv2D(in_channels, out_channels, kernel_size, stride,
                        padding, dilation=dilation, groups=groups,
                        bias_attr=None if bias else False)]
    if norm_layer is not None:
        layers.append(norm_layer(out_channels))
    if activation_layer is not None:
        layers.append(activation_layer())
    return nn.Sequential(*layers)
