"""paddle.vision.datasets (reference: python/paddle/vision/datasets/).

This environment has no network egress, so datasets load from local files
(`data_file=` / `image_path=` args); `FakeData` provides synthetic samples
for pipelines and tests.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData",
           "DatasetFolder", "ImageFolder"]


class FakeData(Dataset):
    """Synthetic image classification dataset (deterministic per index)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = rng.randint(0, self.num_classes)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return self.size


class MNIST(Dataset):
    """MNIST from local idx files (reference downloads them; zero-egress here).

    image_path/label_path point at (possibly gzipped) idx files.
    """

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.transform = transform
        if image_path is None or label_path is None:
            raise ValueError(
                "no network egress: pass image_path/label_path to local "
                "MNIST idx files, or use paddle_tpu.vision.datasets.FakeData")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None]  # 1HW
        label = np.asarray(self.labels[idx], np.int64)
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        return img, label

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """CIFAR-10 from a local python-pickle tarball."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            raise ValueError(
                "no network egress: pass data_file pointing at "
                "cifar-10-python.tar.gz, or use FakeData")
        self.transform = transform
        self.data, self.labels = self._load(data_file, mode)

    def _load(self, data_file, mode):
        images, labels = [], []
        names = [f"data_batch_{i}" for i in range(1, 6)] if mode == "train" \
            else ["test_batch"]
        with tarfile.open(data_file) as tar:
            for member in tar.getmembers():
                if any(member.name.endswith(n) for n in names):
                    d = pickle.load(tar.extractfile(member), encoding="bytes")
                    images.append(d[b"data"])
                    labels.extend(d.get(b"labels", d.get(b"fine_labels")))
        data = np.concatenate(images).reshape(-1, 3, 32, 32)
        return data, np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].astype(np.float32)
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    def _load(self, data_file, mode):
        names = ["train"] if mode == "train" else ["test"]
        images, labels = [], []
        with tarfile.open(data_file) as tar:
            for member in tar.getmembers():
                if any(member.name.endswith(n) for n in names):
                    d = pickle.load(tar.extractfile(member), encoding="bytes")
                    images.append(d[b"data"])
                    labels.extend(d[b"fine_labels"])
        data = np.concatenate(images).reshape(-1, 3, 32, 32)
        return data, np.asarray(labels, np.int64)


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    """class-per-subdir image folder (reference: vision/datasets/folder.py)."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image

            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise RuntimeError("PIL unavailable; use .npy images") from e

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(target, np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """flat folder of images, no labels."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        self.samples = [os.path.join(root, f) for f in sorted(os.listdir(root))
                        if f.lower().endswith(tuple(extensions))]

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Flowers-102 (reference: python/paddle/vision/datasets/flowers.py
    downloads tgz+mat files).  Zero-egress: reads a local directory of
    class-subfolder images if given, else deterministic synthetic blooms."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None,
                 size=510):
        self.mode = mode
        self.transform = transform
        self._folder = None
        if data_file is not None and os.path.isdir(str(data_file)):
            self._folder = DatasetFolder(data_file, transform=transform)
        self.size = len(self._folder) if self._folder else size

    def __getitem__(self, idx):
        if self._folder is not None:
            return self._folder[idx]
        rng = np.random.RandomState(idx + (0 if self.mode == "train" else 1))
        img = rng.rand(3, 96, 96).astype(np.float32)
        label = rng.randint(0, 102)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return self.size


class VOC2012(Dataset):
    """VOC2012 segmentation pairs (reference:
    python/paddle/vision/datasets/voc2012.py — tarball/dir with
    VOCdevkit/VOC2012/{ImageSets/Segmentation/<mode>.txt, JPEGImages/
    <id>.jpg, SegmentationClass/<id>.png}).  Zero-egress: parses a local
    archive or directory when given, else synthetic (image, mask)
    pairs."""

    # reference voc2012.py:37 MODE_FLAG_MAP: 'train' reads the trainval
    # split, 'test' the train split, 'valid' the val split
    _MODE_FLAG_MAP = {"train": "trainval", "test": "train", "valid": "val",
                      "val": "val", "trainval": "trainval"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, size=100):
        if mode not in self._MODE_FLAG_MAP:
            raise ValueError(
                f"mode should be one of {sorted(self._MODE_FLAG_MAP)}, "
                f"got {mode!r}")
        self.mode = mode
        self.flag = self._MODE_FLAG_MAP[mode]
        self.transform = transform
        self.data_file = data_file
        self._ids = None
        if data_file is not None:
            self._open(str(data_file))
        self.size = len(self._ids) if self._ids is not None else size

    def _open(self, path):
        """Index the split LAZILY: decode images per __getitem__ like
        the reference, never the whole split at construction."""
        import tarfile

        if os.path.isdir(path):
            names = [os.path.relpath(os.path.join(dp, f), path)
                     .replace(os.sep, "/")
                     for dp, _, fs in os.walk(path) for f in fs]

            def read_bytes(name):
                with open(os.path.join(path, name), "rb") as f:
                    return f.read()
        else:
            tar = tarfile.open(path)
            members = {m.name: m for m in tar.getmembers()}
            names = list(members)

            def read_bytes(name, _tar=tar, _members=members):
                return _tar.extractfile(_members[name]).read()

        seg_list = [n for n in names if n.endswith(
            f"ImageSets/Segmentation/{self.flag}.txt")]
        if not seg_list:
            raise ValueError(
                f"VOC2012: no ImageSets/Segmentation/{self.flag}.txt "
                f"in {path}")
        self._root = seg_list[0].split("ImageSets/")[0]
        self._ids = read_bytes(seg_list[0]).decode().split()
        self._read_bytes = read_bytes

    def _decode(self, voc_id):
        import io

        from PIL import Image

        img = np.asarray(Image.open(io.BytesIO(self._read_bytes(
            f"{self._root}JPEGImages/{voc_id}.jpg"))).convert("RGB"))
        mask = np.asarray(Image.open(io.BytesIO(self._read_bytes(
            f"{self._root}SegmentationClass/{voc_id}.png"))))
        return (img.transpose(2, 0, 1).astype(np.float32),
                mask.astype(np.int64))

    def __getitem__(self, idx):
        if self._ids is not None:
            img, mask = self._decode(self._ids[idx])
        else:
            rng = np.random.RandomState(idx)
            img = rng.rand(3, 128, 128).astype(np.float32)
            mask = rng.randint(0, 21, (128, 128)).astype(np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return self.size


__all__ += ["Flowers", "VOC2012"]
