"""Image IO (reference: python/paddle/vision/image.py — backend selection
plus image_load over cv2/PIL).  numpy/PIL-backed here; the framework's
device path never decodes images (host-side work feeding the loader)."""
from __future__ import annotations

import numpy as np

_BACKEND = "pil"


def set_image_backend(backend):
    global _BACKEND
    if backend not in ("pil", "cv2", "numpy"):
        raise ValueError(f"unsupported image backend {backend!r}")
    _BACKEND = backend


def get_image_backend():
    return _BACKEND


def image_load(path, backend=None):
    """Load an image file; returns a PIL.Image ('pil') or HWC ndarray."""
    from PIL import Image

    img = Image.open(path)
    if (backend or _BACKEND) == "pil":
        return img
    return np.asarray(img)
