# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Automatic mixed precision (reference: python/paddle/amp/auto_cast.py:21,
grad_scaler.py:26, fluid/dygraph/amp/loss_scaler.py:40).

TPU-native stance: bf16 is the native half type — it shares the f32 exponent
range, so dynamic loss scaling is numerically unnecessary.  The full
GradScaler API is kept for parity (and for fp16 use), implementing the
reference's dynamic scale / inf-check / skip-step state machine
(check_finite_and_unscale + update_loss_scaling ops).
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core.dtype import to_np
from ..core.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "is_auto_cast_enabled", "get_amp_dtype",
           "white_list", "black_list"]

# O1 lists (reference: fluid/dygraph/amp/auto_cast.py WHITE_LIST/BLACK_LIST)
white_list = {"matmul", "bmm", "mm", "linear", "conv1d", "conv2d", "conv3d",
              "einsum", "scaled_dot_product_attention"}
black_list = {"exp", "log", "softmax", "log_softmax", "cross_entropy",
              "mean", "sum", "norm", "cumsum", "logsumexp", "erfinv",
              "layer_norm", "batch_norm"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = "bfloat16"
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def is_auto_cast_enabled():
    return _state.enabled


def get_amp_dtype():
    return _state.dtype if _state.enabled else None


def amp_op_dtype(op_name: str):
    """Consulted by dispatch for O1 cast decisions."""
    if not _state.enabled:
        return None
    if _state.level == "O2":
        return _state.dtype
    wl = (white_list | _state.custom_white) - _state.custom_black
    bl = black_list | _state.custom_black
    if op_name in wl:
        return _state.dtype
    if op_name in bl:
        return "float32"
    return None


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = (_state.enabled, _state.dtype, _state.level, _state.custom_white,
            _state.custom_black)
    _state.enabled = enable
    _state.dtype = dtype
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.custom_white,
         _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to half dtype (reference amp.decorate)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m._convert_dtype(dtype)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


class GradScaler:
    """Dynamic loss scaling (reference AmpScaler loss_scaler.py:40)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        self._unscale(optimizer)

    def _unscale(self, optimizer):
        if not self._enable:
            return
        from ..core.dispatch import in_static_trace

        traced = in_static_trace()
        found_inf = False
        for p, g, _ in optimizer._collect_params_grads():
            if g is None:
                continue
            arr = g._value / self._scale
            if not traced and not bool(jnp.isfinite(arr).all()):
                # eager: host-side inf check drives the skip/update machine.
                # Under to_static (bf16-first) the check is skipped — bf16
                # shares the f32 exponent range so scaling is a no-op there.
                found_inf = True
            g._value = arr
        self._found_inf = found_inf

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self._unscale(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def set_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))
