"""paddle.device (reference: python/paddle/device/).

TPU is the accelerator; `cuda` names exist for API compatibility and map to
the accelerator backend (streams/events are no-ops under the XLA execution
model, where ordering is program order).
"""
from __future__ import annotations

import jax

from ..core.place import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device, set_device,
    is_compiled_with_cuda, is_compiled_with_tpu,
)


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def synchronize(device=None):
    """Block until all queued device work finishes."""
    for d in jax.live_arrays() if hasattr(jax, "live_arrays") else []:
        try:
            d.block_until_ready()
        except Exception:
            pass


class Stream:
    """API-compat stream object: XLA orders work by program order, so
    streams are identity contexts (reference: phi stream objects)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *a):
        return False


class cuda:
    """paddle.device.cuda compat namespace."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        return 0

    @staticmethod
    def memory_allocated(device=None):
        return 0
