# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.device (reference: python/paddle/device/).

TPU is the accelerator; `cuda` names exist for API compatibility and map to
the accelerator backend (streams/events are no-ops under the XLA execution
model, where ordering is program order).
"""
from __future__ import annotations

import jax

from ..core.place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, IPUPlace, MLUPlace,
    NPUPlace, Place, TPUPlace, XPUPlace, device_count, get_device,
    is_compiled_with_cinn, is_compiled_with_cuda, is_compiled_with_ipu,
    is_compiled_with_mlu, is_compiled_with_npu, is_compiled_with_rocm,
    is_compiled_with_tpu, is_compiled_with_xpu, set_device,
)
from ..distributed.env import ParallelEnv  # noqa: F401


def get_all_custom_device_type():
    return []


def get_cudnn_version():
    return None


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def synchronize(device=None):
    """Block until all queued device work finishes."""
    for d in jax.live_arrays() if hasattr(jax, "live_arrays") else []:
        try:
            d.block_until_ready()
        except Exception:
            pass


# ------------------------------------------------------- memory stats
# Reference: paddle/fluid/memory/stats.h (HostMemoryStat* / DeviceMemoryStat*
# with peak tracking) and python/paddle/device/cuda max_memory_allocated.
# TPU-native: PJRT exposes per-device memory_stats() (bytes_in_use,
# peak_bytes_in_use); on backends without stats (CPU) we fall back to
# summing live arrays and track the peak at query time.
_peak_fallback = {"allocated": 0}


def _device_obj(device=None):
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    return device


def _mem_stats(device=None):
    d = _device_obj(device)
    try:
        return d.memory_stats()
    except Exception:
        return None


def memory_allocated(device=None) -> int:
    """Bytes currently held by live buffers on the device."""
    stats = _mem_stats(device)
    if stats:
        return int(stats.get("bytes_in_use", 0))
    total = 0
    for a in (jax.live_arrays() if hasattr(jax, "live_arrays") else []):
        try:
            total += a.nbytes
        except Exception:
            pass
    _peak_fallback["allocated"] = max(_peak_fallback["allocated"], total)
    return total


def max_memory_allocated(device=None) -> int:
    """High-water mark of allocated bytes (PJRT peak_bytes_in_use)."""
    stats = _mem_stats(device)
    if stats:
        return int(stats.get("peak_bytes_in_use",
                             stats.get("bytes_in_use", 0)))
    memory_allocated(device)  # refresh the fallback peak
    return _peak_fallback["allocated"]


def memory_reserved(device=None) -> int:
    """Bytes reserved by the allocator pool (PJRT bytes_reserved +
    in-use; CPU fallback: same as allocated)."""
    stats = _mem_stats(device)
    if stats:
        return int(stats.get("bytes_reserved", 0)
                   + stats.get("bytes_in_use", 0))
    return memory_allocated(device)


def max_memory_reserved(device=None) -> int:
    stats = _mem_stats(device)
    if stats:
        return int(stats.get("peak_bytes_reserved",
                             stats.get("peak_bytes_in_use", 0)))
    return max_memory_allocated(device)


def reset_peak_memory_stats(device=None):
    """Best-effort peak reset (PJRT peaks are monotonic; the fallback
    peak is ours to reset)."""
    _peak_fallback["allocated"] = 0


class Stream:
    """API-compat stream object: XLA orders work by program order, so
    streams are identity contexts (reference: phi stream objects)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *a):
        return False


class cuda:
    """paddle.device.cuda compat namespace."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return max_memory_reserved(device)


    @staticmethod
    def current_stream(device=None):
        return Stream()

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)

    @staticmethod
    def get_device_properties(device=None):
        import jax as _jax

        d = _device_obj(device)
        stats = _mem_stats(device) or {}

        class _Props:
            name = d.device_kind
            major, minor = 0, 0
            total_memory = stats.get("bytes_limit", 0)
            multi_processor_count = 1

            def __repr__(self):
                return (f"_CudaDeviceProperties(name='{self.name}', "
                        f"total_memory={self.total_memory})")

        return _Props()

    @staticmethod
    def get_device_name(device=None):
        return _device_obj(device).device_kind

    @staticmethod
    def get_device_capability(device=None):
        return (0, 0)
