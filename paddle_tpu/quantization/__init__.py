# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Quantization: QAT + PTQ (reference: python/paddle/fluid/contrib/slim —
quantization_pass.py fake_quant insertion, ImperativeQuantAware dygraph QAT,
PTQ calibration; ops paddle/fluid/operators/fake_quantize_op.cc).

TPU-native: fake-quant is a straight-through-estimator op XLA fuses into the
surrounding program; int8 serving uses XLA's native int8 dot when converted.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Parameter, Tensor

__all__ = ["fake_quantize_dequantize", "FakeQuantAbsMax",
           "FakeQuantChannelWiseAbsMax", "FakeQuantMovingAverageAbsMax",
           "QuantedLinear", "QuantedConv2D", "QuantedEmbedding",
           "QuantedMatmul", "ImperativeQuantAware", "PTQ", "AbsmaxObserver",
           "MovingAverageAbsmaxObserver", "Int8Linear", "Int8Conv2D",
           "convert_to_int8"]


def _ste_quant(v, s, qmax):
    """Shared fake-quant body: quantize at scale s (already clamped),
    straight-through gradients.  EVERY fake-quant path (per-tensor,
    per-channel, the static quant_aware pass) and the int8 weight
    quantizer derive from this one rounding rule so they cannot drift."""
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax)
    return v + jax.lax.stop_gradient(q * s / qmax - v)


def _channel_scale(v, quant_axis):
    """Per-channel abs-max scale, keepdims (broadcastable against v)."""
    red = tuple(i for i in range(v.ndim) if i != quant_axis)
    return jnp.maximum(jnp.max(jnp.abs(v), axis=red, keepdims=True), 1e-8)


def fake_quantize_dequantize(x, scale, bit_length=8):
    """Simulated quantization with straight-through gradients."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def _fq(v, s):
        return _ste_quant(v, s, qmax)

    return apply("fake_quant_dequant", _fq, x,
                 scale if isinstance(scale, Tensor) else Tensor(
                     jnp.asarray(scale, jnp.float32)))


class FakeQuantAbsMax(nn.Layer):
    """Per-call abs-max scale (weights)."""

    def __init__(self, bit_length=8):
        super().__init__()
        self.bit_length = bit_length

    def forward(self, x):
        qmax = float(2 ** (self.bit_length - 1) - 1)

        def _fq(v):
            return _ste_quant(v, jnp.max(jnp.abs(v)), qmax)

        return apply("fake_quant_abs_max", _fq, x)


class FakeQuantChannelWiseAbsMax(nn.Layer):
    """Per-channel abs-max weight quantization (reference:
    fake_channel_wise_quantize_dequantize_abs_max op,
    fake_quantize_op.cc; imperative qat.py weight_quantize_type=
    'channel_wise_abs_max').  quant_axis is the CHANNEL axis: 1 for
    Linear [in, out] weights, 0 for Conv2D [out, in, kh, kw]."""

    def __init__(self, bit_length=8, quant_axis=0):
        super().__init__()
        self.bit_length = bit_length
        self.quant_axis = quant_axis

    def forward(self, x):
        qmax = float(2 ** (self.bit_length - 1) - 1)
        axis = self.quant_axis

        def _fq(v):
            return _ste_quant(v, _channel_scale(v, axis), qmax)

        return apply("fake_quant_channel_wise_abs_max", _fq, x)


class FakeQuantMovingAverageAbsMax(nn.Layer):
    """EMA abs-max scale (activations) — reference:
    fake_quantize_moving_average_abs_max op."""

    def __init__(self, bit_length=8, moving_rate=0.9):
        super().__init__()
        self.bit_length = bit_length
        self.moving_rate = moving_rate
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        # Nonzero once the scale reflects real data (QAT training steps
        # or a PTQ convert) — the int8 conversion guard keys off this,
        # since the 1.0 init is indistinguishable from a legitimate
        # scale.  A BUFFER so it survives state_dict round trips (a
        # reloaded QAT model must stay convertible to int8).
        self.register_buffer("calibrated_state",
                             Tensor(jnp.zeros((), jnp.float32)))

    @property
    def calibrated(self) -> bool:
        return float(np.asarray(self.calibrated_state._value)) > 0

    @calibrated.setter
    def calibrated(self, value: bool):
        self.calibrated_state._value = jnp.asarray(
            1.0 if value else 0.0, jnp.float32)

    def forward(self, x):
        if self.training:
            from ..core.dispatch import no_grad_ctx

            with no_grad_ctx():
                cur = jnp.max(jnp.abs(x._value)).astype(jnp.float32)
                self.scale._value = (self.moving_rate * self.scale._value
                                     + (1 - self.moving_rate) * cur)
            self.calibrated = True
        return fake_quantize_dequantize(x, self.scale, self.bit_length)


def _make_weight_quant(kind: str, bits: int, quant_axis: int):
    if kind == "channel_wise_abs_max":
        return FakeQuantChannelWiseAbsMax(bits, quant_axis=quant_axis)
    if kind == "abs_max":
        return FakeQuantAbsMax(bits)
    raise ValueError(
        f"weight_quantize_type must be 'abs_max' or "
        f"'channel_wise_abs_max', got {kind!r}")


class QuantedLinear(nn.Layer):
    def __init__(self, layer: nn.Linear, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max"):
        super().__init__()
        self.inner = layer
        # Linear weight is [in_features, out_features] → channel axis 1
        self.weight_quant = _make_weight_quant(weight_quantize_type,
                                               weight_bits, quant_axis=1)
        self.act_quant = FakeQuantMovingAverageAbsMax(activation_bits)

    def forward(self, x):
        from ..nn.functional.common import linear

        if getattr(self, "_ptq_calibrating", False):
            # PTQ calibration must see RAW activations: fake-quant at the
            # uninitialized 1.0 scale would clip inputs to ±1 and every
            # downstream observer would calibrate on distorted values
            return linear(x, self.inner.weight, self.inner.bias)
        xq = self.act_quant(x)
        wq = self.weight_quant(self.inner.weight)
        return linear(xq, wq, self.inner.bias)


class QuantedConv2D(nn.Layer):
    def __init__(self, layer: nn.Conv2D, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max"):
        super().__init__()
        self.inner = layer
        # Conv2D weight is [out, in, kh, kw] → channel axis 0
        self.weight_quant = _make_weight_quant(weight_quantize_type,
                                               weight_bits, quant_axis=0)
        self.act_quant = FakeQuantMovingAverageAbsMax(activation_bits)

    def forward(self, x):
        from ..nn.functional.conv import conv2d

        if getattr(self, "_ptq_calibrating", False):
            return conv2d(x, self.inner.weight, self.inner.bias,
                          self.inner._stride, self.inner._padding,
                          self.inner._dilation, self.inner._groups,
                          self.inner._data_format)
        xq = self.act_quant(x)
        wq = self.weight_quant(self.inner.weight)
        return conv2d(xq, wq, self.inner.bias, self.inner._stride,
                      self.inner._padding, self.inner._dilation,
                      self.inner._groups, self.inner._data_format)


class QuantedEmbedding(nn.Layer):
    """Weight-quantized embedding (reference: slim quant_embedding pass —
    abs_max table quantization; lookups read the fake-quantized table so
    QAT trains through the STE)."""

    def __init__(self, layer, weight_bits=8):
        super().__init__()
        self.inner = layer
        self.weight_quant = FakeQuantAbsMax(weight_bits)

    def forward(self, x):
        from ..nn.functional.common import embedding

        wq = self.weight_quant(self.inner.weight)
        return embedding(x, wq,
                         padding_idx=getattr(self.inner, "_padding_idx",
                                             None))


class QuantedMatmul(nn.Layer):
    """Fake-quant both operands of a matmul (reference: static
    quantization_pass.py quantizes matmul/matmul_v2 op inputs; imperative
    models route explicit paddle.matmul calls through this wrapper)."""

    def __init__(self, activation_bits=8):
        super().__init__()
        self.x_quant = FakeQuantMovingAverageAbsMax(activation_bits)
        self.y_quant = FakeQuantMovingAverageAbsMax(activation_bits)

    def forward(self, x, y, transpose_x=False, transpose_y=False):
        from ..ops.math import matmul

        return matmul(self.x_quant(x), self.y_quant(y),
                      transpose_x=transpose_x, transpose_y=transpose_y)


class ImperativeQuantAware:
    """Dygraph QAT (reference: slim ImperativeQuantAware): replaces
    Linear/Conv2D/Embedding sublayers with fake-quant wrappers in place;
    weight_quantize_type selects per-tensor or per-channel scales."""

    def __init__(self, quantizable_layer_type=("Linear", "Conv2D"),
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 weight_quantize_type="abs_max", **kwargs):
        self.types = set(quantizable_layer_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.weight_quantize_type = weight_quantize_type

    def quantize(self, model: nn.Layer):
        for layer in model.sublayers(include_self=True):
            for name, sub in list(layer._sub_layers.items()):
                kind = type(sub).__name__
                if kind == "Linear" and "Linear" in self.types:
                    layer._sub_layers[name] = QuantedLinear(
                        sub, self.weight_bits, self.activation_bits,
                        self.weight_quantize_type)
                elif kind == "Conv2D" and "Conv2D" in self.types:
                    layer._sub_layers[name] = QuantedConv2D(
                        sub, self.weight_bits, self.activation_bits,
                        self.weight_quantize_type)
                elif kind == "Embedding" and "Embedding" in self.types:
                    layer._sub_layers[name] = QuantedEmbedding(
                        sub, self.weight_bits)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit

        jit.save(model, path, input_spec=input_spec)


class AbsmaxObserver:
    def __init__(self):
        self.max_val = 0.0

    def observe(self, x: Tensor):
        self.max_val = max(self.max_val,
                           float(jnp.max(jnp.abs(x._value))))

    def scale(self):
        return self.max_val


class MovingAverageAbsmaxObserver:
    """EMA abs-max over calibration batches (reference PTQ algo
    'moving_average_abs_max', post_training_quantization.py) — robust to
    a single outlier batch where plain abs_max is not."""

    def __init__(self, moving_rate=0.9):
        self.moving_rate = moving_rate
        self.ema = None

    def observe(self, x: Tensor):
        cur = float(jnp.max(jnp.abs(x._value)))
        self.ema = cur if self.ema is None else (
            self.moving_rate * self.ema + (1 - self.moving_rate) * cur)

    def scale(self):
        return self.ema or 0.0

    @property
    def max_val(self):
        return self.scale()


# ---------------------------------------------------------------------------
# Int8 EXECUTION (reference: the int8 path the TRT subgraph engine runs
# after calibration, inference/tensorrt/; fake_quantize_op.cc defines the
# quantization math).  TPU-native: int8 weights as buffers, runtime
# activation quant at the frozen scale, lax.dot_general/conv with int8
# inputs accumulating in int32 on the MXU, dequant epilogue in f32.
# ---------------------------------------------------------------------------


def _quantize_weight(w, quant_axis, qmax=127.0, per_channel=True):
    """(w_int8, scale broadcastable against w) — the scale rule mirrors
    the wrapper's fake-quant (per-channel FakeQuantChannelWiseAbsMax or
    per-tensor FakeQuantAbsMax) so QAT and int8 execution match."""
    if per_channel:
        s = _channel_scale(w, quant_axis)
    else:
        s = jnp.maximum(jnp.max(jnp.abs(w)), 1e-8)
        s = s.reshape((1,) * w.ndim)
    q = jnp.clip(jnp.round(w / s * qmax), -qmax, qmax).astype(jnp.int8)
    return q, s.astype(jnp.float32)


class Int8Linear(nn.Layer):
    """Executes y = dequant(int8(x) @ int8(w)) + b.  Built from a trained
    QuantedLinear whose activation scale is frozen."""

    def __init__(self, q: QuantedLinear):
        super().__init__()
        w = q.inner.weight._value.astype(jnp.float32)
        w8, sw = _quantize_weight(   # [in, out] → per-out channel
            w, quant_axis=1,
            per_channel=isinstance(q.weight_quant,
                                   FakeQuantChannelWiseAbsMax))
        self.register_buffer("w_int8", Tensor(w8))
        self.register_buffer("w_scale", Tensor(sw))  # [1, out]
        sx = float(np.asarray(q.act_quant.scale._value))
        if sx <= 0 or not getattr(q.act_quant, "calibrated", False):
            raise ValueError(
                "Int8Linear needs a calibrated activation scale; run QAT "
                "training or PTQ calibration before convert_to_int8")
        self.act_scale = sx
        self.bias = q.inner.bias

    def forward(self, x):
        sx = self.act_scale

        def _int8_linear(xv, w8, sw, bv=None):
            xq = jnp.clip(jnp.round(xv.astype(jnp.float32) / sx * 127.0),
                          -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, w8, (((xq.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            # sw is keepdims ([1, out] per-channel, [1, 1] per-tensor);
            # collapse to the trailing axis so a rank-1 [in] input yields
            # [out] instead of broadcasting up to [1, out]
            out = acc.astype(jnp.float32) * (sx / 127.0) * \
                (sw.reshape(-1) / 127.0)
            if bv is not None:
                out = out + bv.astype(jnp.float32)
            return out.astype(xv.dtype)

        args = (x, self.w_int8, self.w_scale)
        if self.bias is not None:
            args = args + (self.bias,)
        return apply("int8_linear", _int8_linear, *args)


class Int8Conv2D(nn.Layer):
    def __init__(self, q: QuantedConv2D):
        super().__init__()
        inner = q.inner
        if inner._data_format != "NCHW" or inner._groups != 1:
            raise ValueError(
                "Int8Conv2D supports NCHW, groups=1 (got "
                f"{inner._data_format}, groups={inner._groups})")
        w = inner.weight._value.astype(jnp.float32)
        w8, sw = _quantize_weight(   # [out, in, kh, kw]
            w, quant_axis=0,
            per_channel=isinstance(q.weight_quant,
                                   FakeQuantChannelWiseAbsMax))
        self.register_buffer("w_int8", Tensor(w8))
        self.register_buffer("w_scale",
                             Tensor(sw.reshape(1, -1, 1, 1)
                                    if sw.size > 1 else sw))
        sx = float(np.asarray(q.act_quant.scale._value))
        if sx <= 0 or not getattr(q.act_quant, "calibrated", False):
            raise ValueError(
                "Int8Conv2D needs a calibrated activation scale; run QAT "
                "training or PTQ calibration before convert_to_int8")
        self.act_scale = sx
        self.bias = inner.bias
        # normalize with the SAME helpers the f32 conv path uses — Paddle
        # padding may be int, per-dim, [t,b,l,r], pair-list, or SAME/VALID
        from ..nn.functional.conv import _padding as _norm_pad
        from ..nn.functional.conv import _tuplize

        self._stride = _tuplize(inner._stride, 2)
        self._padding = _norm_pad(inner._padding, 2)
        self._dilation = _tuplize(inner._dilation, 2)

    def forward(self, x):
        sx = self.act_scale
        stride, padding, dilation = self._stride, self._padding, \
            self._dilation

        def _int8_conv(xv, w8, sw, bv=None):
            xq = jnp.clip(jnp.round(xv.astype(jnp.float32) / sx * 127.0),
                          -127, 127).astype(jnp.int8)
            acc = jax.lax.conv_general_dilated(
                xq, w8, stride, padding, rhs_dilation=dilation,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (sx / 127.0) * (sw / 127.0)
            if bv is not None:
                out = out + bv.astype(jnp.float32).reshape(1, -1, 1, 1)
            return out.astype(xv.dtype)

        args = (x, self.w_int8, self.w_scale)
        if self.bias is not None:
            args = args + (self.bias,)
        return apply("int8_conv2d", _int8_conv, *args)


def convert_to_int8(model: nn.Layer):
    """Swap trained QuantedLinear/QuantedConv2D wrappers for int8-executing
    layers (reference flow: QAT → quant_post → TRT int8 engine; here the
    'engine' is the same XLA program with i8 dots).  The converted model
    jit.saves like any other; the inference Predictor then provably runs
    int8 (assert `xi8` dot_general in the exported StableHLO)."""
    for layer in model.sublayers(include_self=True):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, QuantedLinear):
                layer._sub_layers[name] = Int8Linear(sub)
            elif isinstance(sub, QuantedConv2D):
                layer._sub_layers[name] = Int8Conv2D(sub)
    return model


class PTQ:
    """Post-training quantization: run calibration batches through observers,
    then freeze scales into fake-quant layers.  algo: 'abs_max' (global
    max over calibration) or 'moving_average_abs_max' (EMA, reference
    post_training_quantization.py algo list)."""

    def __init__(self, activation_bits=8, weight_bits=8, algo="abs_max",
                 weight_quantize_type="abs_max"):
        if algo not in ("abs_max", "moving_average_abs_max"):
            # reference PTQ also lists KL/hist/mse/avg
            # (post_training_quantization.py); unimplemented algos fall
            # back rather than break ported calibration scripts
            import warnings

            warnings.warn(
                f"PTQ algo {algo!r} not implemented on this backend; "
                "falling back to 'abs_max'")
            algo = "abs_max"
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits
        self.algo = algo
        self.weight_quantize_type = weight_quantize_type
        self._observers: Dict[int, AbsmaxObserver] = {}

    def _new_observer(self):
        if self.algo == "moving_average_abs_max":
            return MovingAverageAbsmaxObserver()
        return AbsmaxObserver()

    def quantize(self, model: nn.Layer):
        qat = ImperativeQuantAware(
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits,
            weight_quantize_type=self.weight_quantize_type)
        model = qat.quantize(model)
        model.eval()
        # hooks: observe activation ranges on calibration data; fake-quant
        # is bypassed (_ptq_calibrating) so observers see RAW activations
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                layer._ptq_calibrating = True
                obs = self._new_observer()
                self._observers[id(layer)] = obs

                def hook(l, inputs, _obs=obs):
                    _obs.observe(inputs[0])
                layer.register_forward_pre_hook(hook)
        return model

    def convert(self, model: nn.Layer):
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                layer._ptq_calibrating = False
                obs = self._observers.get(id(layer))
                if obs and obs.max_val > 0:
                    layer.act_quant.scale._value = jnp.asarray(
                        obs.scale(), jnp.float32)
                    layer.act_quant.calibrated = True
        return model


# Reference naming parity: paddle.quantization.QAT wraps the imperative
# quant-aware trainer; quant_post_static is the PTQ entry
# (fluid/contrib/slim/quantization/post_training_quantization.py).
QAT = ImperativeQuantAware


def quant_post_static(model, sample_generator=None, batch_nums=10,
                      algo="abs_max", weight_quantize_type="abs_max",
                      weight_bits=8, activation_bits=8, **kwargs):
    """Post-training quantization: observe activations over calibration
    batches, return the model with quant scales attached."""
    ptq = PTQ(activation_bits=activation_bits, weight_bits=weight_bits,
              algo=algo, weight_quantize_type=weight_quantize_type)
    qmodel = ptq.quantize(model)
    if sample_generator is not None:
        n = 0
        for batch in sample_generator():
            qmodel(*batch if isinstance(batch, (tuple, list)) else (batch,))
            n += 1
            if n >= batch_nums:
                break
    return ptq.convert(qmodel)
