"""Quantization: QAT + PTQ (reference: python/paddle/fluid/contrib/slim —
quantization_pass.py fake_quant insertion, ImperativeQuantAware dygraph QAT,
PTQ calibration; ops paddle/fluid/operators/fake_quantize_op.cc).

TPU-native: fake-quant is a straight-through-estimator op XLA fuses into the
surrounding program; int8 serving uses XLA's native int8 dot when converted.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Parameter, Tensor

__all__ = ["fake_quantize_dequantize", "FakeQuantAbsMax",
           "FakeQuantMovingAverageAbsMax", "QuantedLinear", "QuantedConv2D",
           "ImperativeQuantAware", "PTQ", "AbsmaxObserver"]


def fake_quantize_dequantize(x, scale, bit_length=8):
    """Simulated quantization with straight-through gradients."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def _fq(v, s):
        s = jnp.maximum(s, 1e-8)
        q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax)
        dq = q * s / qmax
        # straight-through: forward quantized, backward identity
        return v + jax.lax.stop_gradient(dq - v)
    return apply("fake_quant_dequant", _fq, x,
                 scale if isinstance(scale, Tensor) else Tensor(
                     jnp.asarray(scale, jnp.float32)))


class FakeQuantAbsMax(nn.Layer):
    """Per-call abs-max scale (weights)."""

    def __init__(self, bit_length=8):
        super().__init__()
        self.bit_length = bit_length

    def forward(self, x):
        qmax = float(2 ** (self.bit_length - 1) - 1)

        def _fq(v):
            s = jnp.maximum(jnp.max(jnp.abs(v)), 1e-8)
            q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax)
            dq = q * s / qmax
            return v + jax.lax.stop_gradient(dq - v)
        return apply("fake_quant_abs_max", _fq, x)


class FakeQuantMovingAverageAbsMax(nn.Layer):
    """EMA abs-max scale (activations) — reference:
    fake_quantize_moving_average_abs_max op."""

    def __init__(self, bit_length=8, moving_rate=0.9):
        super().__init__()
        self.bit_length = bit_length
        self.moving_rate = moving_rate
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))

    def forward(self, x):
        if self.training:
            from ..core.dispatch import no_grad_ctx

            with no_grad_ctx():
                cur = jnp.max(jnp.abs(x._value)).astype(jnp.float32)
                self.scale._value = (self.moving_rate * self.scale._value
                                     + (1 - self.moving_rate) * cur)
        return fake_quantize_dequantize(x, self.scale, self.bit_length)


class QuantedLinear(nn.Layer):
    def __init__(self, layer: nn.Linear, weight_bits=8, activation_bits=8):
        super().__init__()
        self.inner = layer
        self.weight_quant = FakeQuantAbsMax(weight_bits)
        self.act_quant = FakeQuantMovingAverageAbsMax(activation_bits)

    def forward(self, x):
        from ..nn.functional.common import linear

        xq = self.act_quant(x)
        wq = self.weight_quant(self.inner.weight)
        return linear(xq, wq, self.inner.bias)


class QuantedConv2D(nn.Layer):
    def __init__(self, layer: nn.Conv2D, weight_bits=8, activation_bits=8):
        super().__init__()
        self.inner = layer
        self.weight_quant = FakeQuantAbsMax(weight_bits)
        self.act_quant = FakeQuantMovingAverageAbsMax(activation_bits)

    def forward(self, x):
        from ..nn.functional.conv import conv2d

        xq = self.act_quant(x)
        wq = self.weight_quant(self.inner.weight)
        return conv2d(xq, wq, self.inner.bias, self.inner._stride,
                      self.inner._padding, self.inner._dilation,
                      self.inner._groups, self.inner._data_format)


class ImperativeQuantAware:
    """Dygraph QAT (reference: slim ImperativeQuantAware): replaces
    Linear/Conv2D sublayers with fake-quant wrappers in place."""

    def __init__(self, quantizable_layer_type=("Linear", "Conv2D"),
                 weight_bits=8, activation_bits=8, moving_rate=0.9, **kwargs):
        self.types = set(quantizable_layer_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits

    def quantize(self, model: nn.Layer):
        for layer in model.sublayers(include_self=True):
            for name, sub in list(layer._sub_layers.items()):
                if type(sub).__name__ == "Linear" and "Linear" in self.types:
                    layer._sub_layers[name] = QuantedLinear(
                        sub, self.weight_bits, self.activation_bits)
                elif type(sub).__name__ == "Conv2D" and "Conv2D" in self.types:
                    layer._sub_layers[name] = QuantedConv2D(
                        sub, self.weight_bits, self.activation_bits)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit

        jit.save(model, path, input_spec=input_spec)


class AbsmaxObserver:
    def __init__(self):
        self.max_val = 0.0

    def observe(self, x: Tensor):
        self.max_val = max(self.max_val,
                           float(jnp.max(jnp.abs(x._value))))

    def scale(self):
        return self.max_val


class PTQ:
    """Post-training quantization: run calibration batches through observers,
    then freeze scales into fake-quant layers."""

    def __init__(self, activation_bits=8, weight_bits=8):
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits
        self._observers: Dict[int, AbsmaxObserver] = {}

    def quantize(self, model: nn.Layer):
        qat = ImperativeQuantAware(weight_bits=self.weight_bits,
                                   activation_bits=self.activation_bits)
        model = qat.quantize(model)
        model.eval()
        # hooks: observe activation ranges on calibration data
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                obs = AbsmaxObserver()
                self._observers[id(layer)] = obs

                def hook(l, inputs, _obs=obs):
                    _obs.observe(inputs[0])
                layer.register_forward_pre_hook(hook)
        return model

    def convert(self, model: nn.Layer):
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                obs = self._observers.get(id(layer))
                if obs and obs.max_val > 0:
                    layer.act_quant.scale._value = jnp.asarray(
                        obs.scale(), jnp.float32)
        return model


# Reference naming parity: paddle.quantization.QAT wraps the imperative
# quant-aware trainer; quant_post_static is the PTQ entry
# (fluid/contrib/slim/quantization/post_training_quantization.py).
QAT = ImperativeQuantAware


def quant_post_static(model, sample_generator=None, batch_nums=10,
                      algo="abs_max", **kwargs):
    """Post-training quantization: observe activations over calibration
    batches, return the model with quant scales attached."""
    ptq = PTQ()
    qmodel = ptq.quantize(model)
    if sample_generator is not None:
        n = 0
        for batch in sample_generator():
            qmodel(*batch if isinstance(batch, (tuple, list)) else (batch,))
            n += 1
            if n >= batch_nums:
                break
    return ptq.convert(qmodel)
