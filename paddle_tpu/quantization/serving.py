# lint-tpu: disable-file=L004 -- quantization backend math (README: Repo lint)
"""Weight-only quantization for SERVING (inference-mode ``Int8Linear``
path, selected via ``ServingConfig(weight_dtype="int8")``).

Unlike :class:`~paddle_tpu.quantization.Int8Linear` — which swaps
sublayers and needs a calibrated activation scale — the serving model's
attention/MLP forwards consume raw ``layer.weight`` tensors inside fused
ops (``fused_norm_linear`` etc.), so there is no per-layer ``forward``
to intercept.  Instead :func:`quantize_model_weights` quantizes every
Linear-family weight IN PLACE:

* absmax per-out-channel int8 codes + f32 scales are attached to the
  layer as buffers (``weight_int8`` [in, out] i8, ``weight_scale``
  [1, out] f32) — these are the deployable artifacts, and what a TPU
  build keeps resident in HBM;
* ``layer.weight._value`` is rebound to the exact dequantization
  ``codes * scale / 127`` — the matmul-prologue dequant, materialized
  once at quantize time so every fused op and compiled step captures
  int8-representable weights without touching the model's fused-op
  plumbing.  Served math is therefore bit-identical to an on-the-fly
  prologue dequant.

The scale rule is the same ``_quantize_weight`` the QAT→int8 conversion
uses (per-channel ``FakeQuantChannelWiseAbsMax`` convention), so PTQ'd
checkpoints and serving-quantized weights cannot drift.

Because the engine's step cache fingerprints weights by IDENTITY (the
Tensor objects), an in-place ``_value`` rebind would NOT invalidate
already-compiled steps — the quantizer explicitly drops every cached
``_*_step*`` attribute so the next step maker recompiles against the
quantized constants.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import _quantize_weight

__all__ = ["quantize_model_weights", "resolve_weight_dtype"]

logger = logging.getLogger("paddle_tpu.quantization.serving")

_WEIGHT_DTYPE_ALIASES = {
    None: None, "": None, "fp32": None, "float32": None, "auto": None,
    "int8": "int8", "i8": "int8", "w8": "int8", "weight_int8": "int8",
}

# Layer types whose 2-D [in, out] ``weight`` participates in matmuls.
# Norm weights / embedding tables are plain Parameters on other layer
# types and are deliberately untouched (standard weight-only recipes
# keep them full precision).
_LINEAR_TYPES = ("Linear", "ColumnParallelLinear", "RowParallelLinear")


def resolve_weight_dtype(name: Optional[str]) -> Optional[str]:
    """Canonical weight-quant scheme, or None for full precision."""
    key = name.lower() if isinstance(name, str) else name
    try:
        return _WEIGHT_DTYPE_ALIASES[key]
    except KeyError:
        raise ValueError(
            f"unsupported weight_dtype {name!r}; serving weight-only "
            f"quantization supports int8 (aliases: i8, w8) or "
            f"fp32/None") from None


def _invalidate_cached_steps(model) -> int:
    """Drop every compiled step the engine cached on the model — the
    weights they captured as jit constants are stale after an in-place
    quantize (the identity-based fingerprint cannot see the rebind)."""
    stale = [k for k in list(vars(model))
             if "_step" in k and not k.startswith("__")]
    for k in stale:
        delattr(model, k)
    return len(stale)


def quantize_model_weights(model, weight_dtype: Optional[str] = None):
    """Quantize ``model``'s Linear-family weights in place (absmax
    per-out-channel int8).  Idempotent: re-applying the same scheme is a
    no-op; applying a DIFFERENT scheme to an already-quantized model
    raises (the original fp32 weights are gone — requantizing int8
    codes at another width would silently compound error).

    Returns a report dict: ``layers`` quantized, ``fp32_bytes`` the
    weights occupied before, ``quant_bytes`` the int8 codes + scales
    a deployment keeps resident.
    """
    scheme = resolve_weight_dtype(weight_dtype)
    prior = getattr(model, "_serving_weight_dtype", None)
    if scheme is None:
        if prior is not None:
            raise ValueError(
                f"model weights already quantized to {prior}; cannot "
                "restore full precision (reload the checkpoint)")
        return {"layers": 0, "fp32_bytes": 0, "quant_bytes": 0}
    if prior is not None:
        if prior == scheme:
            return dict(model._serving_weight_quant_report)
        raise ValueError(
            f"model weights already quantized to {prior}; cannot "
            f"requantize to {scheme}")

    layers = fp32_bytes = quant_bytes = 0
    for layer in model.sublayers(include_self=True):
        if type(layer).__name__ not in _LINEAR_TYPES:
            continue
        w = getattr(layer, "weight", None)
        if w is None or w._value.ndim != 2:
            continue
        wv = w._value.astype(jnp.float32)
        codes, scale = _quantize_weight(wv, quant_axis=1,
                                        per_channel=True)
        layer.register_buffer("weight_int8", Tensor(codes))
        layer.register_buffer("weight_scale", Tensor(scale))
        # the matmul-prologue dequant, materialized at quantize time
        w._value = (codes.astype(jnp.float32)
                    * (scale / 127.0)).astype(wv.dtype)
        layers += 1
        fp32_bytes += int(wv.size) * 4
        quant_bytes += int(codes.size) + int(scale.size) * 4

    dropped = _invalidate_cached_steps(model)
    report = {"layers": layers, "fp32_bytes": fp32_bytes,
              "quant_bytes": quant_bytes}
    model._serving_weight_dtype = scheme
    model._serving_weight_quant_report = dict(report)
    logger.info(
        "weight-only quant: %d linear layers -> %s (%.2f MiB -> "
        "%.2f MiB resident, %d cached steps invalidated)",
        layers, scheme, fp32_bytes / 2**20, quant_bytes / 2**20,
        dropped)
    return report
