# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.inference: the serving runtime (reference:
paddle/fluid/inference/api/analysis_predictor.cc + paddle_inference_api.h).

TPU-native: the "optimized inference program" IS the jit.save StableHLO
artifact; AnalysisPredictor's 40-pass pipeline collapses into XLA compilation
(with a persistent compile cache).  Zero-copy handles wrap device arrays.
"""
from __future__ import annotations

import enum
import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "create_predictor", "create_serving_endpoint",
           "DistConfig", "DistModel",
           "Predictor", "PredictorPool", "get_version", "DataType",
           "PlaceType", "PrecisionType", "Tensor", "get_trt_compile_version",
           "get_trt_runtime_version", "get_num_bytes_of_data_type",
           "load_c_api"]


def load_c_api():
    """Build + load the stable C inference ABI (reference capi_exp/
    pd_inference_api.h analog; see inference/capi.py)."""
    from .capi import load_c_api as _load

    return _load()


def get_version():
    import paddle_tpu

    return paddle_tpu.__version__


class DataType(enum.Enum):
    """paddle_infer.DataType (reference: paddle_inference_api.h PaddleDType);
    FLOAT16/BFLOAT16 added — TPU serving is natively bf16."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    BOOL = 7


class PlaceType(enum.Enum):
    """paddle_infer.PlaceType (reference: paddle_tensor.h).  GPU enums kept
    for API parity; on this backend everything placed on an accelerator is
    the TPU via PJRT."""
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    NPU = 3
    TPU = 4


class PrecisionType(enum.Enum):
    """paddle_infer.PrecisionType (reference: paddle_analysis_config.h)."""
    Float32 = 0
    Int8 = 1
    Half = 2
    Bfloat16 = 3


_DTYPE_BYTES = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
                DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
                DataType.BFLOAT16: 2, DataType.BOOL: 1}


def get_num_bytes_of_data_type(dtype: "DataType") -> int:
    """reference: paddle/fluid/inference/api/paddle_tensor.h
    paddle_infer::GetNumBytesOfDataType."""
    return _DTYPE_BYTES[DataType(dtype)]


def get_trt_compile_version():
    """No TensorRT on TPU: the compile-time engine is XLA.  (0, 0, 0)
    mirrors the reference's return when built without TRT
    (paddle/fluid/inference/api/analysis_predictor.cc GetTrtCompileVersion)."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


class Config:
    """AnalysisConfig analog."""

    def __init__(self, prog_file=None, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file
        self._model_dir = None
        self._compile_cache_dir = None
        self._memory_pool_mb = 0

    def set_model(self, prog_file, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file

    def set_model_dir(self, d):
        self._model_dir = d

    def model_dir(self):
        return self._model_dir

    def enable_compile_cache(self, cache_dir):
        """Persistent XLA compile cache (the TRT engine-cache analog)."""
        self._compile_cache_dir = cache_dir

    # accepted-and-ignored GPU-era toggles for parity
    def enable_use_gpu(self, memory_pool_mb=100, device_id=0):
        self._memory_pool_mb = memory_pool_mb

    def disable_gpu(self):
        pass

    def enable_memory_optim(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_tensorrt_engine(self, **kwargs):
        # XLA is the engine; accepted for API parity.  But a precision
        # request is a quantization decision the reference would honor
        # (analysis_predictor.cc:975 TensorRT int8 path) — dropping it
        # silently would change serving numerics, so say so.
        precision = kwargs.get("precision_mode")
        if precision is not None and "int8" in str(precision).lower():
            import warnings

            warnings.warn(
                "enable_tensorrt_engine(precision_mode=int8) is ignored: "
                "XLA serves this model at its trained precision; use "
                "paddle_tpu.quantization (PTQ/QAT) for int8")

    def set_cpu_math_library_num_threads(self, n):
        pass


class _IOHandle:
    """ZeroCopyTensor analog."""

    def __init__(self, name):
        self.name = name
        self._array = None

    def reshape(self, shape):
        pass

    def copy_from_cpu(self, data: np.ndarray):
        self._array = jnp.asarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._array)

    def share_external_data(self, data):
        self._array = data._value if hasattr(data, "_value") else data

    @property
    def shape(self):
        return list(self._array.shape) if self._array is not None else None

    def type(self):
        if self._array is None:
            return DataType.FLOAT32
        name = str(self._array.dtype)
        return {"float32": DataType.FLOAT32, "int64": DataType.INT64,
                "int32": DataType.INT32, "uint8": DataType.UINT8,
                "int8": DataType.INT8, "float16": DataType.FLOAT16,
                "bfloat16": DataType.BFLOAT16,
                "bool": DataType.BOOL}.get(name, DataType.FLOAT32)


# public name: paddle.inference.Tensor is the reference's ZeroCopyTensor
# handle type (paddle/fluid/inference/api/paddle_tensor.h) — users touch it
# via predictor.get_input_handle(); exported so isinstance checks port over.
Tensor = _IOHandle


def _load_exported(config: Config):
    """Shared model-loading path for Predictor and DistModel: honors the
    persistent compile cache, loads the jit-saved artifact."""
    from ..jit import load as jit_load

    if config._compile_cache_dir:
        try:
            jax.config.update("jax_compilation_cache_dir",
                              config._compile_cache_dir)
        except Exception:
            pass
    return jit_load(config.prog_file or config._model_dir)


class Predictor:
    def __init__(self, config: Config):
        self.config = config
        self._loaded = _load_exported(config)
        n_in = len(self._loaded._exported.in_avals) if hasattr(
            self._loaded._exported, "in_avals") else 1
        self._inputs = {f"input_{i}": _IOHandle(f"input_{i}")
                        for i in range(n_in)}
        self._outputs: Dict[str, _IOHandle] = {}

    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name) -> _IOHandle:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._outputs) or ["output_0"]

    def get_output_handle(self, name) -> _IOHandle:
        return self._outputs.setdefault(name, _IOHandle(name))

    def run(self, inputs: Optional[list] = None):
        """ZeroCopyRun: execute the compiled program."""
        if inputs is not None:
            arrs = [x._value if hasattr(x, "_value") else jnp.asarray(x)
                    for x in inputs]
        else:
            arrs = [h._array for h in self._inputs.values()]
        out = self._loaded._exported.call(*arrs)
        leaves = jax.tree_util.tree_leaves(out)
        for i, leaf in enumerate(leaves):
            self.get_output_handle(f"output_{i}")._array = leaf
        if inputs is not None:
            from ..core.tensor import Tensor

            return [Tensor(l) for l in leaves]
        return True

    def clone(self):
        return Predictor(self.config)

    def clear_intermediate_tensor(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def create_serving_endpoint(model, config=None, **generate_defaults):
    """Continuous-batching LLM front door: a Predictor-shaped
    :class:`paddle_tpu.serving.Endpoint` over a live causal LM (the
    Predictor above serves jit.save artifacts; this serves token
    streams with iteration-level batching — see paddle_tpu/serving/).

    ``model`` may also be a prebuilt :class:`paddle_tpu.serving.Engine`
    or a :class:`paddle_tpu.serving.Router` fleet (``config`` must then
    be None — a prebuilt engine already carries its config).
    ``config`` is a :class:`paddle_tpu.serving.ServingConfig`;
    ``generate_defaults`` (eos_token_id, max_new_tokens, ...) apply to
    every request unless overridden per call."""
    from ..serving import Endpoint

    return Endpoint(model, config, **generate_defaults)


class PredictorPool:
    def __init__(self, config: Config, size: int = 1):
        self._predictors = [create_predictor(config) for _ in range(size)]

    def retrieve(self, idx) -> Predictor:
        return self._predictors[idx]


class DistConfig:
    """Distributed-inference settings (reference:
    paddle/fluid/distributed/fleet_executor/dist_model.h DistModelConfig —
    ranks/endpoints for the interceptor runtime).  TPU-native: serving
    shards one compiled program over a device mesh, so the knobs are the
    mesh axes rather than endpoints."""

    def __init__(self):
        self.batch_axis = "dp"
        self.devices = None      # default: all local devices
        self.carrier_id = "inference"
        self.rank = 0
        self.nranks = 1
        self._enabled = True

    def enable_dist_model(self, flag=True):
        self._enabled = bool(flag)

    def set_ranks(self, nranks, rank):
        self.nranks, self.rank = int(nranks), int(rank)


class DistModel:
    """Sharded serving (reference: dist_model.cc DistModel::Run — the
    distributed inference entry over the fleet executor).  The loaded
    program executes once across a mesh with the batch dim sharded over
    the data axis; parameters are replicated (TP-sharded serving reuses
    the training shardings via fleet + a normal compiled call instead)."""

    def __init__(self, config: Config, dist_config: DistConfig = None):
        self.config = config
        self.dist_config = dist_config or DistConfig()
        self._loaded = _load_exported(config)
        devs = self.dist_config.devices or jax.devices()
        import numpy as np

        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        self._mesh = Mesh(np.asarray(devs),
                          (self.dist_config.batch_axis,))
        self._batch_sharding = NamedSharding(
            self._mesh, PartitionSpec(self.dist_config.batch_axis))

    def run(self, inputs):
        """Batch-sharded execution; returns output Tensors.  The shardings
        actually applied to each input are kept on
        ``last_input_shardings`` for observability/tests."""
        from ..core.tensor import Tensor

        arrs = []
        self.last_input_shardings = []
        n_dev = len(self._mesh.devices.ravel())
        for x in inputs:
            v = x._value if hasattr(x, "_value") else jnp.asarray(x)
            if self.dist_config._enabled and v.ndim                     and v.shape[0] % n_dev == 0:
                v = jax.device_put(v, self._batch_sharding)
            arrs.append(v)
            self.last_input_shardings.append(getattr(v, "sharding", None))
        out = self._loaded._exported.call(*arrs)
        return [Tensor(leaf) for leaf in jax.tree_util.tree_leaves(out)]
