"""paddle.inference: the serving runtime (reference:
paddle/fluid/inference/api/analysis_predictor.cc + paddle_inference_api.h).

TPU-native: the "optimized inference program" IS the jit.save StableHLO
artifact; AnalysisPredictor's 40-pass pipeline collapses into XLA compilation
(with a persistent compile cache).  Zero-copy handles wrap device arrays.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "create_predictor", "Predictor", "PredictorPool",
           "get_version"]


def get_version():
    import paddle_tpu

    return paddle_tpu.__version__


class Config:
    """AnalysisConfig analog."""

    def __init__(self, prog_file=None, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file
        self._model_dir = None
        self._compile_cache_dir = None
        self._memory_pool_mb = 0

    def set_model(self, prog_file, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file

    def set_model_dir(self, d):
        self._model_dir = d

    def model_dir(self):
        return self._model_dir

    def enable_compile_cache(self, cache_dir):
        """Persistent XLA compile cache (the TRT engine-cache analog)."""
        self._compile_cache_dir = cache_dir

    # accepted-and-ignored GPU-era toggles for parity
    def enable_use_gpu(self, memory_pool_mb=100, device_id=0):
        self._memory_pool_mb = memory_pool_mb

    def disable_gpu(self):
        pass

    def enable_memory_optim(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_tensorrt_engine(self, **kwargs):
        pass  # XLA is the engine

    def set_cpu_math_library_num_threads(self, n):
        pass


class _IOHandle:
    """ZeroCopyTensor analog."""

    def __init__(self, name):
        self.name = name
        self._array = None

    def reshape(self, shape):
        pass

    def copy_from_cpu(self, data: np.ndarray):
        self._array = jnp.asarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._array)

    def share_external_data(self, data):
        self._array = data._value if hasattr(data, "_value") else data

    @property
    def shape(self):
        return list(self._array.shape) if self._array is not None else None


class Predictor:
    def __init__(self, config: Config):
        from ..jit import load as jit_load

        self.config = config
        if config._compile_cache_dir:
            try:
                jax.config.update("jax_compilation_cache_dir",
                                  config._compile_cache_dir)
            except Exception:
                pass
        path = config.prog_file or config._model_dir
        self._loaded = jit_load(path)
        n_in = len(self._loaded._exported.in_avals) if hasattr(
            self._loaded._exported, "in_avals") else 1
        self._inputs = {f"input_{i}": _IOHandle(f"input_{i}")
                        for i in range(n_in)}
        self._outputs: Dict[str, _IOHandle] = {}

    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name) -> _IOHandle:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._outputs) or ["output_0"]

    def get_output_handle(self, name) -> _IOHandle:
        return self._outputs.setdefault(name, _IOHandle(name))

    def run(self, inputs: Optional[list] = None):
        """ZeroCopyRun: execute the compiled program."""
        if inputs is not None:
            arrs = [x._value if hasattr(x, "_value") else jnp.asarray(x)
                    for x in inputs]
        else:
            arrs = [h._array for h in self._inputs.values()]
        out = self._loaded._exported.call(*arrs)
        leaves = jax.tree_util.tree_leaves(out)
        for i, leaf in enumerate(leaves):
            self.get_output_handle(f"output_{i}")._array = leaf
        if inputs is not None:
            from ..core.tensor import Tensor

            return [Tensor(l) for l in leaves]
        return True

    def clone(self):
        return Predictor(self.config)

    def clear_intermediate_tensor(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class PredictorPool:
    def __init__(self, config: Config, size: int = 1):
        self._predictors = [create_predictor(config) for _ in range(size)]

    def retrieve(self, idx) -> Predictor:
        return self._predictors[idx]
