"""Loader for the stable C inference ABI (reference:
paddle/fluid/inference/capi_exp/pd_inference_api.h + goapi/ — the C
surface external serving stacks link against).

The shim (core/native/pd_inference_c.cc) embeds CPython over the Python
Predictor: C consumers get PD_ConfigCreate / PD_ConfigSetModel /
PD_PredictorCreate / PD_PredictorRunFloat / PD_BufferFree /
PD_GetLastError with the reference's naming.  ``load_c_api()`` builds
(g++, first use) and returns the ctypes CDLL with argtypes configured —
the same handle a C program gets from dlopen."""
from __future__ import annotations

import ctypes
import sysconfig


def _python_link_flags():
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    flags = [f"-I{inc}"]
    if libdir:
        flags.append(f"-L{libdir}")
    flags.append(f"-lpython{ver}")
    return flags


def load_c_api():
    """Build + dlopen libpd_inference_c.so; returns a configured CDLL."""
    from ..core.native.build import load_native

    lib = load_native("pd_inference_c", extra_flags=_python_link_flags())
    lib.PD_ConfigCreate.restype = ctypes.c_void_p
    lib.PD_ConfigSetModel.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p]
    lib.PD_ConfigDestroy.argtypes = [ctypes.c_void_p]
    lib.PD_PredictorCreate.argtypes = [ctypes.c_void_p]
    lib.PD_PredictorCreate.restype = ctypes.c_void_p
    lib.PD_PredictorDestroy.argtypes = [ctypes.c_void_p]
    lib.PD_BufferFree.argtypes = [ctypes.c_void_p]
    lib.PD_GetLastError.restype = ctypes.c_char_p
    lib.PD_PredictorRunFloat.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.PD_PredictorRunFloat.restype = ctypes.c_int
    return lib
