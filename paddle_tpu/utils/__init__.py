"""paddle.utils (reference: python/paddle/utils/)."""
from __future__ import annotations

from . import cpp_extension  # noqa: F401


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or str(e)) from e


def run_check():
    """paddle.utils.run_check analog: verify the accelerator works."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle

    x = paddle.ones([2, 2])
    y = (x @ x).numpy()
    assert y[0, 0] == 2.0
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! backend="
          f"{jax.default_backend()}, {n} device(s)")
    return True


def unique_name_generator(prefix="tmp"):
    import itertools

    counter = itertools.count()

    def gen():
        return f"{prefix}_{next(counter)}"

    return gen


class deprecated:
    def __init__(self, update_to="", since="", reason=""):
        self.update_to = update_to

    def __call__(self, fn):
        return fn
