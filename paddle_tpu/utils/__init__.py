# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.utils (reference: python/paddle/utils/)."""
from __future__ import annotations

from . import cpp_extension  # noqa: F401


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or str(e)) from e


def run_check():
    """paddle.utils.run_check analog: verify the accelerator works."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle

    x = paddle.ones([2, 2])
    y = (x @ x).numpy()
    assert y[0, 0] == 2.0
    # install-check banner reports the whole visible fleet on purpose
    n = len(jax.devices())  # lint-tpu: disable=H112
    print(f"paddle_tpu is installed successfully! backend="
          f"{jax.default_backend()}, {n} device(s)")
    return True


def unique_name_generator(prefix="tmp"):
    import itertools

    counter = itertools.count()

    def gen():
        return f"{prefix}_{next(counter)}"

    return gen


class deprecated:
    def __init__(self, update_to="", since="", reason=""):
        self.update_to = update_to

    def __call__(self, fn):
        return fn


def require_version(min_version, max_version=None):
    """Assert the installed framework version is in range (reference:
    python/paddle/utils/install_check.py require_version)."""
    from .. import __version__

    def key(v):
        parts = [int(p) for p in str(v).split(".")[:3] if p.isdigit()]
        return tuple(parts + [0] * (3 - len(parts)))  # zero-pad: 0.1==0.1.0

    cur = key(__version__)
    if key(min_version) > cur:
        raise Exception(
            f"version {min_version} required, installed {__version__}")
    if max_version is not None and key(max_version) < cur:
        raise Exception(
            f"version <= {max_version} required, installed {__version__}")
    return True


class unique_name:
    """Name generator namespace (reference:
    python/paddle/utils/unique_name.py generate/guard/switch)."""

    _counters = {}
    _prefix = []

    @classmethod
    def generate(cls, key):
        full = "/".join(cls._prefix + [key]) if cls._prefix else key
        n = cls._counters.get(full, 0)
        cls._counters[full] = n + 1
        return f"{full}_{n}"

    @classmethod
    def switch(cls, new_generator=None):
        """Swap the counter state; pass a previously returned state to
        restore it (reference switch/restore idiom)."""
        old = (dict(cls._counters), list(cls._prefix))
        if new_generator is None:
            cls._counters = {}
            cls._prefix = []
        else:
            counters, prefix = new_generator
            cls._counters = dict(counters)
            cls._prefix = list(prefix)
        return old

    @classmethod
    def guard(cls, new_generator=None):
        from contextlib import contextmanager

        @contextmanager
        def ctx():
            saved = dict(cls._counters)
            prefix_saved = list(cls._prefix)
            if new_generator:
                cls._prefix.append(str(new_generator).rstrip("_"))
            cls._counters = {}
            try:
                yield
            finally:
                cls._counters = saved
                cls._prefix = prefix_saved

        return ctx()


class download:
    """paddle.utils.download (reference: python/paddle/utils/download.py).
    No network egress in this environment: resolution is cache-only —
    get_weights_path_from_url returns the cached file when present and
    raises with instructions otherwise."""

    @staticmethod
    def get_weights_path_from_url(url, md5sum=None):
        import os

        cache = os.environ.get(
            "PADDLE_TPU_WEIGHTS_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                         "weights"))
        fname = url.split("/")[-1]
        path = os.path.join(cache, fname)
        if os.path.exists(path):
            if md5sum is not None:
                import hashlib

                with open(path, "rb") as f:
                    digest = hashlib.md5(f.read()).hexdigest()
                if digest != md5sum:
                    raise RuntimeError(
                        f"cached {fname} md5 {digest} != expected "
                        f"{md5sum}; delete {path} and re-stage it")
            return path
        raise RuntimeError(
            f"no network egress: place {fname} under {cache} (from {url})")


from . import dlpack  # noqa: E402,F401
from .dlpack import from_dlpack, to_dlpack  # noqa: E402,F401
