# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.utils.dlpack (reference: paddle/fluid/framework/dlpack_tensor.cc):
zero-copy tensor exchange with other frameworks via the DLPack protocol."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def to_dlpack(x: Tensor):
    return jax.dlpack.to_dlpack(x._value) if hasattr(jax.dlpack, "to_dlpack") \
        else x._value.__dlpack__()


class _CapsuleHolder:
    """Adapter for RAW PyCapsules (torch.utils.dlpack.to_dlpack returns
    one): newer jax/numpy only accept objects with __dlpack__/
    __dlpack_device__.  A capsule carries no device info, so this assumes
    host memory (kDLCPU) — raw-capsule handoff between frameworks is a
    host-side path; device arrays come in as __dlpack__-bearing objects."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, stream=None, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # kDLCPU, device 0


def from_dlpack(capsule) -> Tensor:
    if hasattr(capsule, "__dlpack__"):
        arr = jnp.from_dlpack(capsule)
    else:  # raw PyCapsule
        import numpy as np

        arr = jnp.asarray(np.from_dlpack(_CapsuleHolder(capsule)))
    return Tensor(arr)
