"""paddle.utils.dlpack (reference: paddle/fluid/framework/dlpack_tensor.cc):
zero-copy tensor exchange with other frameworks via the DLPack protocol."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def to_dlpack(x: Tensor):
    return jax.dlpack.to_dlpack(x._value) if hasattr(jax.dlpack, "to_dlpack") \
        else x._value.__dlpack__()


def from_dlpack(capsule) -> Tensor:
    if hasattr(capsule, "__dlpack__"):
        arr = jnp.from_dlpack(capsule)
    else:
        arr = jax.dlpack.from_dlpack(capsule)
    return Tensor(arr)
