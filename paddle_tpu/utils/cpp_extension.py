"""Custom C++ op loading (reference: python/paddle/utils/cpp_extension —
JIT builds of user .cc ops against paddle/extension.h; custom_operator.cc
loads them at runtime).

TPU-native custom-op story: (1) host-side C++ via this module (ctypes ABI —
the TCPStore pattern), (2) device-side custom kernels are Pallas functions
registered with register_pallas_op.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Callable, Dict

_PALLAS_OPS: Dict[str, Callable] = {}


def get_build_directory(verbose=False):
    """Default extension build dir (reference:
    python/paddle/utils/cpp_extension/extension_utils.py get_build_directory
    — honors PADDLE_EXTENSION_DIR, else a per-user cache dir)."""
    root = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")
    if verbose:
        print(f"paddle_tpu extensions build dir: {root}")
    return root


def load(name, sources, extra_cxx_cflags=None, extra_include_paths=None,
         build_directory=None, verbose=False):
    """Compile C++ sources into a shared lib and load with ctypes."""
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    so_path = os.path.join(build_dir, f"lib{name}.so")
    srcs = [sources] if isinstance(sources, str) else list(sources)
    needs_build = not os.path.exists(so_path) or any(
        os.path.getmtime(s) > os.path.getmtime(so_path) for s in srcs)
    if needs_build:
        cmd = (["g++", "-O2", "-fPIC", "-shared", "-std=c++17"]
               + (extra_cxx_cflags or [])
               + [f"-I{p}" for p in (extra_include_paths or [])]
               + srcs + ["-o", so_path])
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(so_path)


class CppExtension:
    def __init__(self, sources, *args, **kwargs):
        self.sources = sources


class CUDAExtension(CppExtension):
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "no CUDA on TPU: device kernels are Pallas (register_pallas_op)")


def register_pallas_op(name: str, fn: Callable):
    """Register a Pallas kernel as a named custom op, callable through
    paddle_tpu.utils.cpp_extension.get_op(name) — the custom-kernel registry
    analog (reference: phi/core/custom_kernel.cc)."""
    _PALLAS_OPS[name] = fn
    return fn


def get_op(name: str) -> Callable:
    return _PALLAS_OPS[name]


class BuildExtension:
    @staticmethod
    def with_options(**kwargs):
        return BuildExtension


def setup(**kwargs):
    raise NotImplementedError("use cpp_extension.load for JIT builds")
