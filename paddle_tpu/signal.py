# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.signal — frame / overlap_add / stft / istft.

Reference: python/paddle/signal.py:32 (frame), :154 (overlap_add),
:237 (stft), :391 (istft).  The reference lowers frame/overlap_add to
dedicated C++ kernels (frame_op.cc, overlap_add_op.cc) and stft to
fft_r2c/fft_c2c; here everything is a gather / scatter-add expressed in
jnp so XLA fuses the window multiply into the FFT's pre-pass and the
whole stft compiles to one fusion + FFT call on TPU.  All four are
differentiable through the tape (one grad node per public call).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .core.dispatch import apply
from .core.tensor import Tensor, to_tensor

__all__ = ["stft", "istft"]  # reference __all__; frame/overlap_add public too


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _frame_idx(seq_len: int, frame_length: int, hop_length: int):
    """(frame_length, num_frames) gather indices: idx[i, j] = j*hop + i."""
    num_frames = 1 + (seq_len - frame_length) // hop_length
    return (np.arange(frame_length)[:, None]
            + hop_length * np.arange(num_frames)[None, :])


def _frame_val(v, frame_length, hop_length, axis):
    seq_len = v.shape[axis]
    if not 0 < frame_length <= seq_len:
        raise ValueError(
            f"frame_length should be in (0, seq_length({seq_len})], "
            f"but got {frame_length}")
    idx = _frame_idx(seq_len, frame_length, hop_length)
    if axis == 0:
        # [num_frames, frame_length, ...] (also the 1D convention)
        return v[idx.T]
    # axis == -1: advanced index on the last axis ->
    # [..., frame_length, num_frames]
    return v[..., idx]


def _check_int(val, what):
    if not isinstance(val, (int, np.integer)) or isinstance(val, bool):
        raise ValueError(
            f"{what} should be a positive integer, got {val!r}")


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames (reference signal.py:32).

    axis=-1: [..., seq] -> [..., frame_length, num_frames];
    axis=0:  [seq, ...] -> [num_frames, frame_length, ...].
    """
    _check_int(frame_length, "frame_length")
    _check_int(hop_length, "hop_length")
    if hop_length <= 0:
        raise ValueError(f"hop_length should be > 0, but got {hop_length}")
    if axis not in (0, -1):
        raise ValueError(f"axis should be 0 or -1, but got {axis}")
    return apply("frame",
                 lambda v: _frame_val(v, frame_length, hop_length, axis),
                 _t(x))


def _overlap_add_val(v, hop_length, axis):
    if axis != 0:
        frame_length, num_frames = v.shape[-2], v.shape[-1]
        seq_len = (num_frames - 1) * hop_length + frame_length
        idx = jnp.asarray(_frame_idx(seq_len, frame_length, hop_length))
        out = jnp.zeros(v.shape[:-2] + (seq_len,), v.dtype)
        # repeated indices accumulate under .at[].add — this IS overlap-add
        return out.at[..., idx].add(v)
    num_frames, frame_length = v.shape[0], v.shape[1]
    seq_len = (num_frames - 1) * hop_length + frame_length
    idx = jnp.asarray(_frame_idx(seq_len, frame_length, hop_length).T)
    out = jnp.zeros((seq_len,) + v.shape[2:], v.dtype)
    return out.at[idx].add(v)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of ``frame`` by scatter-add (reference signal.py:154)."""
    _check_int(hop_length, "hop_length")
    if hop_length <= 0:
        raise ValueError(f"hop_length should be > 0, but got {hop_length}")
    if axis not in (0, -1):
        raise ValueError(f"axis should be 0 or -1, but got {axis}")
    x = _t(x)
    if len(x.shape) < 2:
        raise ValueError(
            f"overlap_add expects a tensor of rank >= 2 "
            f"([..., frame_length, num_frames] or "
            f"[num_frames, frame_length, ...]), got rank {len(x.shape)}")
    return apply("overlap_add",
                 lambda v: _overlap_add_val(v, hop_length, axis), x)


def _pad_center(w, n_fft):
    win_length = w.shape[0]
    if win_length < n_fft:
        left = (n_fft - win_length) // 2
        w = jnp.pad(w, (left, n_fft - win_length - left))
    return w


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (reference signal.py:237).

    Real input + onesided=True -> [..., n_fft//2 + 1, num_frames] complex;
    onesided=False -> [..., n_fft, num_frames].
    """
    x = _t(x)
    if x._value.dtype not in (jnp.float32, jnp.float64, jnp.complex64,
                              jnp.complex128):
        raise TypeError(
            f"stft expects float32/float64/complex64/complex128 input, "
            f"got {x._value.dtype}")
    x_rank = len(x.shape)
    if x_rank not in (1, 2):
        raise ValueError(
            f"x should be a 1D or 2D tensor, but got rank {x_rank}")
    _check_int(n_fft, "n_fft")
    if hop_length is None:
        hop_length = n_fft // 4
    _check_int(hop_length, "hop_length")
    if hop_length <= 0:
        raise ValueError(f"hop_length should be > 0, but got {hop_length}")
    if win_length is None:
        win_length = n_fft
    if not 0 < win_length <= n_fft:
        raise ValueError(
            f"win_length should be in (0, n_fft({n_fft})], got {win_length}")
    if not 0 < n_fft <= x.shape[-1]:
        raise ValueError(
            f"n_fft should be in (0, seq_length({x.shape[-1]})], got {n_fft}")
    is_complex_in = jnp.iscomplexobj(x._value)
    if window is not None:
        window = _t(window)
        if len(window.shape) != 1 or window.shape[0] != win_length:
            raise ValueError(
                f"expected a 1D window of size win_length({win_length}), "
                f"got shape {tuple(window.shape)}")
        if jnp.iscomplexobj(window._value):
            is_complex_in = True  # windowed frames become complex
    if is_complex_in and onesided:
        raise ValueError(
            "onesided should be False when input or window is a complex "
            "Tensor")

    def _stft_val(v, w):
        squeeze = v.ndim == 1
        if squeeze:
            v = v[None]
        if w is None:
            w = jnp.ones((win_length,), v.real.dtype if is_complex_in
                         else v.dtype)
        w = _pad_center(w, n_fft)
        if center:
            if pad_mode not in ("constant", "reflect"):
                raise ValueError(
                    f'pad_mode should be "reflect" or "constant", '
                    f'got "{pad_mode}"')
            p = n_fft // 2
            v = jnp.pad(v, ((0, 0), (p, p)), mode=pad_mode)
        frames = _frame_val(v, n_fft, hop_length, -1)   # (B, n_fft, T)
        frames = jnp.swapaxes(frames, -1, -2) * w        # (B, T, n_fft)
        norm = "ortho" if normalized else "backward"
        if is_complex_in:
            out = jnp.fft.fft(frames, axis=-1, norm=norm)
        elif onesided:
            out = jnp.fft.rfft(frames, axis=-1, norm=norm)
        else:
            out = jnp.fft.fft(frames.astype(
                jnp.complex64 if v.dtype == jnp.float32 else jnp.complex128),
                axis=-1, norm=norm)
        out = jnp.swapaxes(out, -1, -2)                  # (B, F, T)
        return out[0] if squeeze else out

    if window is None:
        return apply("stft", lambda v: _stft_val(v, None), x)
    return apply("stft", _stft_val, x, window)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT — least-squares (Griffin-Lim optimal) reconstruction
    via overlap-add and window-envelope normalization (reference
    signal.py:391).  NOLA violations raise when values are concrete.
    """
    x = _t(x)
    x_rank = len(x.shape)
    if x_rank not in (2, 3):
        raise ValueError(
            f"x should be a 2D or 3D complex tensor, got rank {x_rank}")
    if not jnp.iscomplexobj(x._value):
        raise TypeError("istft expects a complex input (output of stft)")
    _check_int(n_fft, "n_fft")
    if hop_length is None:
        hop_length = n_fft // 4
    _check_int(hop_length, "hop_length")
    if win_length is None:
        win_length = n_fft
    _check_int(win_length, "win_length")
    if not 0 < hop_length <= win_length:
        raise ValueError(
            f"hop_length should be in (0, win_length({win_length})], "
            f"got {hop_length}")
    if not 0 < win_length <= n_fft:
        raise ValueError(
            f"win_length should be in (0, n_fft({n_fft})], got {win_length}")
    fft_size = x.shape[-2]
    want = n_fft // 2 + 1 if onesided else n_fft
    if fft_size != want:
        raise ValueError(
            f"fft_size should be {want} for onesided={onesided}, "
            f"got {fft_size}")
    if return_complex and onesided:
        raise ValueError("onesided should be False when return_complex")
    if window is not None:
        window = _t(window)
        if len(window.shape) != 1 or window.shape[0] != win_length:
            raise ValueError(
                f"expected a 1D window of size win_length({win_length}), "
                f"got shape {tuple(window.shape)}")
        if not return_complex and jnp.iscomplexobj(window._value):
            raise TypeError(
                "window should not be complex when return_complex is False")

    # NOLA check — depends only on (window, hop, n_fft, n_frames, center,
    # length), never on the signal, so it runs eagerly on the concrete
    # window value: inside the kernel the envelope is a Tracer whenever the
    # window participates in grad recording and the check would be silently
    # skipped there.  Skipped only if the window itself is a traced jit
    # argument (reference static mode skips it the same way, signal.py:568).
    n_frames = int(x.shape[-1])
    if window is None:
        w_val = np.ones(win_length, np.float64)
    elif isinstance(window._value, jax.core.Tracer):
        w_val = None
    else:
        w_val = np.asarray(window._value)
    if w_val is not None:
        left = (n_fft - win_length) // 2
        w_pad = np.zeros(n_fft, w_val.dtype)
        w_pad[left:left + win_length] = w_val
        env = np.zeros((n_frames - 1) * hop_length + n_fft, w_pad.dtype)
        np.add.at(env, _frame_idx(env.size, n_fft, hop_length),
                  (w_pad * w_pad)[:, None])
        lo = n_fft // 2 if center else 0
        hi = lo + length if length is not None else \
            env.size - (n_fft // 2 if center else 0)
        if np.any(np.abs(env[lo:hi]) < 1e-11):
            raise ValueError(
                "window overlap-add envelope has (near-)zeros: NOLA "
                "condition not met for this window/hop_length")

    def _istft_val(v, w):
        squeeze = v.ndim == 2
        if squeeze:
            v = v[None]
        n_frames = v.shape[-1]
        real_dt = (jnp.float32 if v.dtype == jnp.complex64 else jnp.float64)
        if w is None:
            w = jnp.ones((win_length,), real_dt)
        w = _pad_center(w, n_fft)
        frames = jnp.swapaxes(v, -1, -2)                 # (B, T, F)
        norm = "ortho" if normalized else "backward"
        if return_complex:
            out = jnp.fft.ifft(frames, axis=-1, norm=norm)
        else:
            if not onesided:
                frames = frames[..., :n_fft // 2 + 1]
            out = jnp.fft.irfft(frames, n=n_fft, axis=-1, norm=norm)
        out = out * w                                     # (B, T, n_fft)
        out = _overlap_add_val(jnp.swapaxes(out, -1, -2), hop_length, -1)
        env = _overlap_add_val(
            jnp.broadcast_to((w * w)[:, None], (n_fft, n_frames)),
            hop_length, -1)                               # (seq,)
        if length is None:
            lo = n_fft // 2 if center else 0
            hi = out.shape[-1] - (n_fft // 2 if center else 0)
        else:
            lo = n_fft // 2 if center else 0
            hi = lo + length
        out, env = out[..., lo:hi], env[lo:hi]
        # Unconditional divide: when the eager NOLA check above ran, env
        # has no (near-)zeros here; when it was skipped (traced window) a
        # violation surfaces as inf/nan like the reference (signal.py:574)
        # rather than being silently masked.
        out = out / env
        return out[0] if squeeze else out

    if window is None:
        return apply("istft", lambda v: _istft_val(v, None), x)
    return apply("istft", _istft_val, x, window)
