"""True static-graph mode: Program / Block / Variable / Executor.

TPU-native re-design of the reference static graph stack
(/root/reference/python/paddle/fluid/framework.py Program:4777 Block:3199
Operator:2533 Variable:1212, executor.py:1103 Executor.run, backward.py
append_backward, layers/control_flow.py cond/while_loop) on top of XLA:

* A ``Program`` records ops symbolically.  Ops are the SAME functional jnp
  computations the eager mode dispatches (core/dispatch.py): while static
  mode is enabled, ``dispatch.apply`` routes any op that touches a symbolic
  ``Variable`` to :func:`record_op`, which infers output shapes with
  ``jax.eval_shape`` (the InferShape analog) and appends an ``OpDesc`` to the
  current ``Block``.  Ops over concrete tensors (initializers, constants)
  still execute eagerly — build-time constant folding.
* ``Executor.run`` interprets the recorded program inside ONE ``jax.jit``:
  the whole program — forward, backward, optimizer updates — compiles to a
  single XLA executable per feed signature (the InterpreterCore +
  build-strategy-fusion equivalent; XLA does the fusion).
* ``append_backward`` records a single ``backward`` op whose interpretation
  is ``jax.grad`` over the re-interpreted forward prefix; XLA CSE merges the
  recomputation with the primal forward, recovering the reference's
  grad-op-transpilation semantics without per-op grad kernels.
* Control flow becomes sub-``Block``s on the op (the reference's
  conditional_block_op / while_op design) lowered to ``lax.cond`` /
  ``lax.while_loop``.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
from collections import ChainMap
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.export  # noqa: F401 — jax.export is lazy; attribute access alone fails
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.dtype import convert_dtype, to_np
from ..core.tensor import Parameter, Tensor

_NOT_RECORDED = dispatch.NOT_RECORDED  # recorder declined: run eagerly


# =====================================================================
# Variables
# =====================================================================
class Variable(Tensor):
    """Symbolic tensor in a Program.  ``_value`` is a ShapeDtypeStruct, so
    ``.shape``/``.dtype``/``.ndim`` work transparently in layer code."""

    def __init__(self, aval: jax.ShapeDtypeStruct, name: str, block: "Block",
                 persistable: bool = False, stop_gradient: bool = True,
                 declared_shape=None):
        super().__init__(aval, stop_gradient=stop_gradient, name=name)
        self.block = block
        self.persistable = persistable
        self.declared_shape = declared_shape  # may contain None/-1 dims
        self.is_data = False

    @property
    def desc(self):
        return self

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' is symbolic; run it through "
            "Executor.run(fetch_list=[var]) to get a value")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype.name})")

    __str__ = __repr__


# =====================================================================
# Program representation
# =====================================================================
class OpDesc:
    __slots__ = ("type", "fn", "attrs", "inputs", "treedef", "outputs",
                 "single", "writeback", "extra")

    def __init__(self, type, fn, attrs, inputs, treedef, outputs, single,
                 writeback=None, extra=None):
        self.type = type
        self.fn = fn
        self.attrs = attrs
        # inputs: list of (kind, ref); kind in {'var','const','raw','dyn'}
        #   var  -> Variable,  const -> eager Tensor (live object, e.g. Param)
        #   raw  -> python value, dyn -> zero-arg provider called every run
        self.inputs = inputs
        self.treedef = treedef
        self.outputs = outputs
        self.single = single
        self.writeback = writeback or []  # [(out_index, setter)]
        self.extra = extra or {}


class Block:
    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.ops: List[OpDesc] = []
        self.vars: Dict[str, Variable] = {}

    def create_var(self, aval, name=None, persistable=False,
                   stop_gradient=True, declared_shape=None) -> Variable:
        name = name or self.program._unique_name("tmp")
        v = Variable(aval, name, self, persistable=persistable,
                     stop_gradient=stop_gradient, declared_shape=declared_shape)
        self.vars[name] = v
        return v

    def var(self, name) -> Variable:
        if name in self.vars:
            return self.vars[name]
        if self.parent_idx >= 0:
            return self.program.blocks[self.parent_idx].var(name)
        raise KeyError(f"no variable named {name!r}")

    def append_op(self, op: OpDesc):
        self.ops.append(op)
        self.program._version += 1


class Program:
    """Recorded op graph (the ProgramDesc analog,
    /root/reference/paddle/fluid/framework/framework.proto:236)."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.random_seed = 0
        self._for_test = False
        self._version = 0
        self._name_counter = itertools.count()
        self._exec_cache: Dict[Any, Any] = {}
        # persistable initialization actions: [(tensor, init_fn)]
        self._startup_actions: List[Tuple[Tensor, Callable]] = []

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        st = _state()
        if st.block_stack and st.block_stack[-1].program is self:
            return st.block_stack[-1]
        return self.blocks[0]

    def _create_block(self, parent: Block) -> Block:
        b = Block(self, len(self.blocks), parent.idx)
        self.blocks.append(b)
        return b

    def _unique_name(self, prefix: str) -> str:
        return f"{prefix}_{next(self._name_counter)}"

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def clone(self, for_test: bool = False) -> "Program":
        # ops reference live objects; a clone shares them.  for_test=True
        # marks the clone so the Executor prunes backward/optimizer/state
        # writeback ops (the reference's clone(for_test=True) prunes the
        # backward program and flips is_test attrs)
        p = Program()
        p.blocks = self.blocks
        p.random_seed = self.random_seed
        p._version = self._version
        p._startup_actions = self._startup_actions
        p._for_test = for_test
        return p

    def verify(self, fetch_list=None, strict: bool = True,
               reinfer: bool = True):
        """Structural + shape/dtype verification (analysis.verifier).

        Returns the diagnostics list; with ``strict`` (default) raises
        ``paddle_tpu.analysis.ProgramVerificationError`` on any
        error-severity finding.  ``fetch_list`` enables dead-op and
        unfetchable-output detection.
        """
        from ..analysis import verify_program

        return verify_program(self, fetch_list=fetch_list, strict=strict,
                              reinfer=reinfer)

    def __repr__(self):
        lines = []
        for b in self.blocks:
            lines.append(f"block {b.idx} (parent {b.parent_idx}):")
            for op in b.ops:
                ins = [r.name if k == "var" else k for k, r in op.inputs]
                outs = [o.name for o in op.outputs]
                lines.append(f"  {op.type}({ins}) -> {outs}")
        return "\n".join(lines)


# =====================================================================
# Mode + builder state
# =====================================================================
class _BuilderState(threading.local):
    def __init__(self):
        self.static_mode = False
        self.main_program: Optional[Program] = None
        self.startup_program: Optional[Program] = None
        self.block_stack: List[Block] = []
        self.paused = 0


_builder = _BuilderState()


def _state() -> _BuilderState:
    return _builder


def enable_static():
    st = _state()
    if not st.static_mode:
        st.static_mode = True
        if st.main_program is None:
            st.main_program = Program()
            st.startup_program = Program()
        dispatch.set_graph_recorder(_recorder)


def disable_static():
    st = _state()
    st.static_mode = False
    dispatch.set_graph_recorder(None)


def in_static_mode() -> bool:
    return _state().static_mode


def default_main_program() -> Program:
    st = _state()
    if st.main_program is None:
        st.main_program = Program()
        st.startup_program = Program()
    return st.main_program


def default_startup_program() -> Program:
    default_main_program()
    return _state().startup_program


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    st = _state()
    prev = (st.main_program, st.startup_program)
    st.main_program = main_program
    if startup_program is not None:
        st.startup_program = startup_program
    try:
        yield
    finally:
        st.main_program, st.startup_program = prev


@contextlib.contextmanager
def pause_recording():
    st = _state()
    st.paused += 1
    try:
        yield
    finally:
        st.paused -= 1


def _current_block() -> Block:
    st = _state()
    if st.block_stack:
        return st.block_stack[-1]
    return default_main_program().global_block()


@contextlib.contextmanager
def _sub_block():
    st = _state()
    parent = _current_block()
    blk = parent.program._create_block(parent)
    st.block_stack.append(blk)
    try:
        yield blk
    finally:
        st.block_stack.pop()


# =====================================================================
# Recording
# =====================================================================
def data(name, shape, dtype="float32", lod_level=0) -> Variable:
    """paddle.static.data analog: a feed placeholder."""
    blk = default_main_program().global_block()
    declared = list(shape)
    concrete = tuple(1 if (d is None or d < 0) else int(d) for d in declared)
    aval = jax.ShapeDtypeStruct(concrete, to_np(dtype))
    v = blk.create_var(aval, name=name, declared_shape=declared)
    v.is_data = True
    return v


def _recorder(name, fn, args, attrs):
    """Installed into dispatch.apply while static mode is on."""
    st = _state()
    if st.paused:
        return _NOT_RECORDED
    flat, treedef = jax.tree_util.tree_flatten(
        args, is_leaf=lambda x: isinstance(x, Tensor))
    if not any(isinstance(leaf, Variable) for leaf in flat):
        return _NOT_RECORDED  # constant folding: run eagerly
    return record_op(name, fn, flat, treedef, attrs)


def record_op(name, fn, flat, treedef, attrs):
    blk = _current_block()
    inputs = []
    specs = []
    spec_pos = []
    for i, leaf in enumerate(flat):
        if isinstance(leaf, Variable):
            inputs.append(("var", leaf))
            specs.append(leaf._value)
            spec_pos.append(i)
        elif isinstance(leaf, Tensor):
            inputs.append(("const", leaf))
            specs.append(jax.ShapeDtypeStruct(leaf._value.shape,
                                              leaf._value.dtype))
            spec_pos.append(i)
        else:
            inputs.append(("raw", leaf))

    def shape_fn(*vals):
        out = _call_op_fn(fn, flat, treedef, spec_pos, vals, attrs)
        return out

    from ..ops import random as rnd

    prev = rnd.set_trace_key_provider(lambda: jax.random.PRNGKey(0))
    try:
        out_aval = jax.eval_shape(shape_fn, *specs)
    finally:
        rnd.set_trace_key_provider(prev)

    single = not isinstance(out_aval, (tuple, list))
    out_list = [out_aval] if single else list(out_aval)
    outputs = [blk.create_var(
        jax.ShapeDtypeStruct(tuple(o.shape), o.dtype),
        name=blk.program._unique_name(name)) for o in out_list]
    blk.append_op(OpDesc(name, fn, attrs, inputs, treedef, outputs, single))
    return outputs[0] if single else tuple(outputs)


def _call_op_fn(fn, flat, treedef, spec_pos, vals, attrs):
    new_flat = list(flat)
    for pos, v in zip(spec_pos, vals):
        new_flat[pos] = v
    # non-tensor leaves stay; tensor leaves replaced by raw values (op fns
    # receive raw arrays, as in dispatch.apply's raw_fn)
    for i, leaf in enumerate(new_flat):
        if isinstance(leaf, Tensor):
            new_flat[i] = leaf._value
    if treedef is None:  # flat convention (optimizer update ops)
        return fn(*new_flat, **attrs)
    args = jax.tree_util.tree_unflatten(treedef, new_flat)
    return fn(*args, **attrs)


def record_writeback_op(name, fn, leaves, targets):
    """Record an op (flat call convention) whose outputs are written back
    into live eager tensors after every Executor.run — the mechanism for
    persistable state mutated inside the program (BN running stats,
    optimizer slots; the reference models these as ops writing Scope vars).

    leaves: list of Variable | Tensor | zero-arg provider | raw python value.
    targets: list of eager Tensors to receive the outputs, aligned 1:1.
    """
    blk = _current_block()
    entries = []
    for leaf in leaves:
        if isinstance(leaf, Variable):
            entries.append(("var", leaf))
        elif isinstance(leaf, Tensor):
            entries.append(("const", leaf))
        elif callable(leaf):
            entries.append(("dyn", leaf))
        else:
            entries.append(("raw", leaf))
    outputs = [blk.create_var(
        jax.ShapeDtypeStruct(tuple(t._value.shape), t._value.dtype),
        name=blk.program._unique_name(name)) for t in targets]

    def make_setter(t):
        def set_(v):
            t._value = v
        return set_

    writeback = [(i, make_setter(t)) for i, t in enumerate(targets)]
    blk.append_op(OpDesc(name, fn, {}, entries, None, outputs,
                         single=len(targets) == 1, writeback=writeback))
    return outputs


# =====================================================================
# append_backward / gradients
# =====================================================================
def _collect_referenced_params(block: Block, upto: int):
    seen, out = set(), []
    for op in block.ops[:upto]:
        for kind, ref in op.inputs:
            if (kind == "const" and isinstance(ref, Tensor)
                    and getattr(ref, "persistable", False)
                    and getattr(ref, "trainable", True)
                    and not ref.stop_gradient
                    and id(ref) not in seen):
                seen.add(id(ref))
                out.append(ref)
    return out


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Record grad computation for ``loss`` wrt parameters; returns
    [(param, grad_var)] like the reference
    (/root/reference/python/paddle/fluid/backward.py append_backward)."""
    blk = loss.block
    prefix_len = len(blk.ops)
    if parameter_list:
        params = [p for p in parameter_list
                  if no_grad_set is None or getattr(p, "name", None) not in no_grad_set]
    else:
        params = _collect_referenced_params(blk, prefix_len)
        if no_grad_set:
            params = [p for p in params
                      if getattr(p, "name", None) not in no_grad_set]
    return _record_backward(loss, params, prefix_len)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.static.gradients: grads of sum of targets wrt arbitrary vars,
    with optional cotangents (reference: fluid/backward.py gradients)."""
    targets = list(targets) if isinstance(targets, (list, tuple)) else [targets]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None and not isinstance(
            target_gradients, (list, tuple)):
        target_gradients = [target_gradients]
    blk = targets[0].block
    return [g for _, g in _record_backward(
        targets, inputs, len(blk.ops),
        target_gradients=target_gradients, no_grad_set=no_grad_set)]


def _entry(x):
    return ("var", x) if isinstance(x, Variable) else ("const", x)


def _record_backward(targets: Sequence, wrt: Sequence, prefix_len: int,
                     target_gradients=None, no_grad_set=None):
    if isinstance(targets, Variable):
        targets = [targets]
    blk = targets[0].block
    entries = []
    grad_vars = []
    for w in wrt:
        if isinstance(w, Variable):
            entries.append(("var", w))
            aval = jax.ShapeDtypeStruct(tuple(w.shape), w._value.dtype)
            gname = f"{w.name}@GRAD"
        else:
            entries.append(("const", w))
            aval = jax.ShapeDtypeStruct(tuple(w._value.shape), w._value.dtype)
            gname = f"{getattr(w, 'name', None) or f'param_{id(w)}'}@GRAD"
        grad_vars.append(blk.create_var(aval, name=blk.program._unique_name(gname)))

    tg_entries = None
    if target_gradients is not None:
        tg_entries = [None if tg is None else _entry(tg)
                      for tg in target_gradients]
    no_grad_names = set(no_grad_set) if no_grad_set else set()

    op = OpDesc("backward", None, {},
                [_entry(t) for t in targets] + entries, None,
                grad_vars, single=False,
                extra={"prefix_len": prefix_len, "n_targets": len(targets),
                       "target_gradients": tg_entries,
                       "no_grad_names": no_grad_names})
    blk.append_op(op)
    return list(zip(wrt, grad_vars))


# =====================================================================
# Control flow (sub-block ops; reference: conditional_block_op / while_op)
# =====================================================================
def _wrap_branch_outputs(outs):
    if outs is None:
        return [], True
    single = not isinstance(outs, (tuple, list))
    return ([outs] if single else list(outs)), single


def static_cond(pred, true_fn, false_fn, operands=()):
    blk = _current_block()
    with _sub_block() as tb:
        t_out, t_single = _wrap_branch_outputs(true_fn(*operands))
    with _sub_block() as fb:
        f_out, f_single = _wrap_branch_outputs(false_fn(*operands))
    assert len(t_out) == len(f_out), "cond branches must match in structure"

    outputs = []
    for o in t_out:
        aval = (jax.ShapeDtypeStruct(tuple(o.shape),
                                     o._value.dtype if isinstance(o, Tensor)
                                     else jnp.result_type(o))
                if isinstance(o, Tensor)
                else jax.ShapeDtypeStruct(np.shape(o), jnp.result_type(o)))
        outputs.append(blk.create_var(aval, name=blk.program._unique_name("cond")))

    op = OpDesc("cond", None, {},
                [("var", pred) if isinstance(pred, Variable) else ("const", pred)],
                None, outputs, single=t_single,
                extra={"true_block": tb, "false_block": fb,
                       "true_out": t_out, "false_out": f_out})
    blk.append_op(op)
    return outputs[0] if t_single else tuple(outputs)


def static_while_loop(cond_fn, body_fn, loop_vars):
    blk = _current_block()
    loop_vars = list(loop_vars)
    shadows = []
    for i, v in enumerate(loop_vars):
        if isinstance(v, Variable):
            aval = v._value
        elif isinstance(v, Tensor):
            aval = jax.ShapeDtypeStruct(tuple(v._value.shape), v._value.dtype)
        else:
            arr = jnp.asarray(v)
            aval = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
        shadows.append(blk.create_var(aval, name=blk.program._unique_name(f"loopvar{i}")))

    with _sub_block() as cb:
        pred_out = cond_fn(*shadows)
    with _sub_block() as bb:
        body_out = body_fn(*shadows)
        body_out, _ = _wrap_branch_outputs(body_out)
    assert len(body_out) == len(loop_vars), \
        "while_loop body must return one value per loop var"

    outputs = [blk.create_var(s._value, name=blk.program._unique_name("whileout"))
               for s in shadows]
    entries = [("var", v) if isinstance(v, Variable)
               else ("const", v) if isinstance(v, Tensor)
               else ("raw", v) for v in loop_vars]
    op = OpDesc("while", None, {}, entries, None, outputs, single=False,
                extra={"cond_block": cb, "body_block": bb,
                       "pred_out": pred_out, "body_out": body_out,
                       "shadows": shadows})
    blk.append_op(op)
    return tuple(outputs)


# =====================================================================
# Interpretation (inside jax.jit)
# =====================================================================
class _Interp:
    def __init__(self, capmap, dyn_env, key_provider):
        self.capmap = capmap          # id(const Tensor) -> value
        self.dyn_env = dyn_env        # id(provider) -> value
        self.key_provider = key_provider
        self.wb_vals: Dict[int, Any] = {}   # id(setter) -> value
        self.depth = 0                # >0 while inside a control-flow branch

    def leaf_value(self, kind, ref, env):
        if kind == "var":
            return env[ref.name]
        if kind == "const":
            return self.capmap.get(id(ref), ref._value)
        if kind == "dyn":
            return self.dyn_env[id(ref)]
        return ref

    def run_block(self, block: Block, env) -> None:
        # only called for control-flow sub-blocks: values created here are
        # branch-local tracers, so writebacks must not be captured
        self.depth += 1
        try:
            for op in block.ops:
                self.run_op(op, env)
        finally:
            self.depth -= 1

    def run_op(self, op: OpDesc, env) -> None:
        if op.type == "backward":
            self._run_backward(op, env)
            return
        if op.type == "cond":
            self._run_cond(op, env)
            return
        if op.type == "while":
            self._run_while(op, env)
            return
        vals, pos = [], []
        flat = []
        for i, (kind, ref) in enumerate(op.inputs):
            flat.append(ref)
            if kind != "raw":
                vals.append(self.leaf_value(kind, ref, env))
                pos.append(i)
        from ..ops import random as rnd

        prev = rnd.set_trace_key_provider(self.key_provider)
        try:
            out = _call_op_fn(op.fn, flat, op.treedef, pos, vals, op.attrs)
        finally:
            rnd.set_trace_key_provider(prev)
        out_list = [out] if op.single else list(out)
        for var, v in zip(op.outputs, out_list):
            env[var.name] = v
        if self.depth == 0:
            for out_idx, setter in op.writeback:
                self.wb_vals[id(setter)] = out_list[out_idx]

    def _run_backward(self, op: OpDesc, env) -> None:
        n_t = op.extra.get("n_targets", 1)
        target_entries, wrt = op.inputs[:n_t], op.inputs[n_t:]
        tg_entries = op.extra.get("target_gradients")
        no_grad_names = op.extra.get("no_grad_names") or set()
        first_target = target_entries[0][1]
        prefix = first_target.block.ops[:op.extra["prefix_len"]]
        cur = [self.leaf_value(k, r, env) for k, r in wrt]

        def f(*wrt_vals):
            env2 = dict(env)
            sub = _Interp(dict(self.capmap), self.dyn_env, self.key_provider)
            for (kind, ref), v in zip(wrt, wrt_vals):
                if kind == "var":
                    env2[ref.name] = v
                else:
                    sub.capmap[id(ref)] = v
            for p_op in prefix:
                sub.run_op(p_op, env2)
                if no_grad_names:
                    for o in p_op.outputs:
                        if o.name in no_grad_names:
                            env2[o.name] = jax.lax.stop_gradient(
                                env2[o.name])
            # scalar objective: sum of targets, each contracted with its
            # cotangent when given (reference fills ones otherwise)
            total = jnp.float32(0.0)
            for i, (kind, ref) in enumerate(target_entries):
                tv = sub.leaf_value(kind, ref, env2).astype(jnp.float32)
                if tg_entries is not None and tg_entries[i] is not None:
                    cot = self.leaf_value(*tg_entries[i], env)
                    total = total + jnp.sum(tv * cot.astype(jnp.float32))
                else:
                    total = total + jnp.sum(tv)
            return total

        grads = jax.grad(f, argnums=tuple(range(len(wrt))))(*cur)
        for gvar, g, (kind, ref) in zip(op.outputs, grads, wrt):
            tgt_dtype = (ref._value.dtype if isinstance(ref, Tensor)
                         else g.dtype)
            env[gvar.name] = g.astype(tgt_dtype)

    def _branch_value(self, o, env2):
        if isinstance(o, Variable):
            return env2[o.name]
        if isinstance(o, Tensor):
            return self.capmap.get(id(o), o._value)
        return jnp.asarray(o)

    def _run_cond(self, op: OpDesc, env) -> None:
        pred = self.leaf_value(*op.inputs[0], env)

        def make_branch(blk, outs):
            def br(_):
                env2 = ChainMap({}, env)
                self.run_block(blk, env2)
                return tuple(self._branch_value(o, env2) for o in outs)
            return br

        res = jax.lax.cond(
            jnp.asarray(pred).astype(bool).reshape(()),
            make_branch(op.extra["true_block"], op.extra["true_out"]),
            make_branch(op.extra["false_block"], op.extra["false_out"]),
            0)
        for var, v in zip(op.outputs, res):
            env[var.name] = v

    def _run_while(self, op: OpDesc, env) -> None:
        shadows = op.extra["shadows"]
        init = tuple(self.leaf_value(k, r, env) for k, r in op.inputs)

        def bind(carry):
            env2 = ChainMap({}, env)
            for s, v in zip(shadows, carry):
                env2[s.name] = v
            return env2

        def cond_f(carry):
            env2 = bind(carry)
            self.run_block(op.extra["cond_block"], env2)
            p = self._branch_value(op.extra["pred_out"], env2)
            return jnp.asarray(p).astype(bool).reshape(())

        def body_f(carry):
            env2 = bind(carry)
            self.run_block(op.extra["body_block"], env2)
            return tuple(
                jnp.asarray(self._branch_value(o, env2)).astype(
                    jnp.asarray(c).dtype).reshape(jnp.asarray(c).shape)
                for o, c in zip(op.extra["body_out"], carry))

        res = jax.lax.while_loop(cond_f, body_f, init)
        for var, v in zip(op.outputs, res):
            env[var.name] = v


# =====================================================================
# Executor
# =====================================================================
def _sub_block_ops(op: OpDesc):
    for key in ("true_block", "false_block", "cond_block", "body_block"):
        blk = op.extra.get(key)
        if blk is not None:
            for sub in blk.ops:
                yield sub
                yield from _sub_block_ops(sub)


def _prune_ops(block: Block, fetch_refs, include_writebacks: bool):
    """Keep only ops the fetches (and, for training, state writebacks)
    depend on — the reference's program pruning (fluid/backward.py
    _prune_and_optimize / inference memory_optimize)."""
    needed = {r.name for r in fetch_refs if isinstance(r, Variable)}
    needed |= {r for r in fetch_refs if isinstance(r, str)}
    keep = [False] * len(block.ops)
    force_prefix = 0  # backward ops re-run their prefix at grad eval

    def op_var_inputs(op):
        for kind, ref in op.inputs:
            if kind == "var":
                yield ref.name
        for sub in _sub_block_ops(op):
            for kind, ref in sub.inputs:
                if kind == "var":
                    yield ref.name

    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        k = (any(o.name in needed for o in op.outputs)
             or (include_writebacks and op.writeback)
             or i < force_prefix)
        if k:
            keep[i] = True
            needed.update(op_var_inputs(op))
            if op.type == "backward":
                force_prefix = max(force_prefix, op.extra["prefix_len"])
    # second pass for prefixes forced by a backward op seen late
    changed = True
    while changed:
        changed = False
        for i in range(len(block.ops) - 1, -1, -1):
            op = block.ops[i]
            if not keep[i] and (i < force_prefix
                                or any(o.name in needed for o in op.outputs)):
                keep[i] = True
                needed.update(op_var_inputs(op))
                if op.type == "backward":
                    force_prefix = max(force_prefix, op.extra["prefix_len"])
                changed = True
    return [op for op, k in zip(block.ops, keep) if k]


def _collect_const_and_dyn(op_list):
    consts, dyns, setters = [], [], []
    cseen, dseen = set(), set()

    def visit(op, collect_wb):
        for kind, ref in op.inputs:
            if kind == "const" and id(ref) not in cseen:
                cseen.add(id(ref))
                consts.append(ref)
            elif kind == "dyn" and id(ref) not in dseen:
                dseen.add(id(ref))
                dyns.append(ref)
        if collect_wb:
            for _, setter in op.writeback:
                setters.append(setter)

    for op in op_list:
        visit(op, collect_wb=True)
        for sub in _sub_block_ops(op):
            # sub-block writebacks are branch-local tracers — they cannot
            # escape the lax.cond/while trace, so state written inside
            # control flow is not persisted (documented limitation)
            visit(sub, collect_wb=False)
        if op.type == "backward":
            # grad eval re-runs the prefix: its consts are inputs too —
            # they are already visited because prefix ops are kept
            pass
    return consts, dyns, setters


class _CompiledProgram:
    def __init__(self, program: Program, feed_names, fetch_refs,
                 include_writebacks: bool = True):
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_refs = list(fetch_refs)
        self.op_list = _prune_ops(program.global_block(), self.fetch_refs,
                                  include_writebacks)
        self.consts, self.dyns, self.setters = _collect_const_and_dyn(
            self.op_list)

        produced = {o.name for op in self.op_list for o in op.outputs}
        required = set()
        for op in self.op_list:
            for kind, ref in op.inputs:
                if kind == "var" and ref.name not in produced:
                    required.add(ref.name)
        for ref in self.fetch_refs:
            if isinstance(ref, Variable) and ref.name not in produced \
                    and ref.is_data:
                required.add(ref.name)
        missing = required - set(self.feed_names)
        if missing:
            raise ValueError(
                f"feed is missing required input(s) {sorted(missing)}; "
                f"the program consumes feeds {sorted(required)}")
        blk = program.global_block()
        self.feed_decls = {n: blk.vars[n].declared_shape or blk.vars[n].shape
                           for n in self.feed_names if n in blk.vars}

        comp = self

        def jfn(feed_vals, const_vals, dyn_vals, rng_key):
            counter = itertools.count()

            def key_provider():
                return jax.random.fold_in(rng_key, next(counter))

            capmap = {id(t): v for t, v in zip(comp.consts, const_vals)}
            dyn_env = {id(p): v for p, v in zip(comp.dyns, dyn_vals)}
            interp = _Interp(capmap, dyn_env, key_provider)
            env: Dict[str, Any] = dict(zip(comp.feed_names, feed_vals))
            for op in comp.op_list:
                interp.run_op(op, env)
            fetches = []
            for ref in comp.fetch_refs:
                if isinstance(ref, Variable):
                    fetches.append(env[ref.name])
                elif isinstance(ref, Tensor):
                    fetches.append(capmap.get(id(ref), ref._value))
                else:  # name
                    fetches.append(env[ref])
            # keep positional alignment with comp.setters (None = no value)
            wb = [interp.wb_vals.get(id(s)) for s in comp.setters]
            return tuple(fetches), tuple(wb)

        self._jitted = jax.jit(jfn)

    def run(self, feed_vals, rng_key):
        for name, v in zip(self.feed_names, feed_vals):
            decl = self.feed_decls.get(name)
            if decl is None:
                continue
            ok = len(v.shape) == len(decl) and all(
                d is None or d < 0 or d == s
                for d, s in zip(decl, v.shape))
            if not ok:
                raise ValueError(
                    f"feed '{name}' has shape {tuple(v.shape)} but the "
                    f"program declares {list(decl)}")
        const_vals = [t._value for t in self.consts]
        dyn_vals = [jnp.asarray(p()) for p in self.dyns]
        fetches, wb = self._jitted(feed_vals, const_vals, dyn_vals, rng_key)
        for setter, v in zip(self.setters, wb):
            if v is not None:
                setter(v)
        return fetches


class Executor:
    """paddle.static.Executor analog: compiles + runs Programs on XLA."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, scope=None, return_numpy=True, **kwargs):
        from ..ops import random as rnd

        if program is None:
            program = default_main_program()
        if isinstance(program, CompiledProgramWrapper):
            program = program._program
        if isinstance(program, LoadedInferenceProgram):
            # reference contract: the program returned by
            # load_inference_model runs through exe.run(prog, feed,
            # fetch_list=fetch_targets) like any other program — and a
            # SUBSET or reordering of fetch_targets is valid, so map the
            # requested names onto the stored output order (ADVICE r4)
            outs = program.run(feed or {})
            if fetch_list is None:
                return outs
            req = fetch_list if isinstance(fetch_list, (list, tuple)) \
                else [fetch_list]
            positions = {}
            dupes = set()
            for i, n in enumerate(program.fetch_names):
                if n in positions:
                    dupes.add(n)
                else:
                    positions[n] = i
            picked = []
            for r in req:
                name = r if isinstance(r, str) else getattr(r, "name", r)
                if name in dupes:
                    raise ValueError(
                        f"fetch target {name!r} is ambiguous: multiple "
                        "outputs share that name in the saved program")
                if name not in positions:
                    raise KeyError(
                        f"fetch target {name!r} not among this loaded "
                        f"program's outputs {program.fetch_names}")
                picked.append(outs[positions[name]])
            return picked
        feed = feed or {}
        fetch_list = fetch_list or []
        if not isinstance(fetch_list, (list, tuple)):
            fetch_list = [fetch_list]

        # startup program: (re)run parameter initializers
        if not program.global_block().ops and program._startup_actions:
            with pause_recording():
                for tensor, init_fn in program._startup_actions:
                    tensor._value = init_fn()
            return []

        feed_items = sorted(feed.items())
        feed_names = [k for k, _ in feed_items]
        with pause_recording():
            feed_vals = [v._value if isinstance(v, Tensor) else jnp.asarray(v)
                         for _, v in feed_items]

        fetch_key = tuple(
            r.name if isinstance(r, Variable) else
            f"@const{id(r)}" if isinstance(r, Tensor) else str(r)
            for r in fetch_list)
        key = (program._version, tuple(feed_names),
               tuple((tuple(v.shape), str(v.dtype)) for v in feed_vals),
               fetch_key)
        comp = program._exec_cache.get(key)
        if comp is None:
            comp = _CompiledProgram(program, feed_names, fetch_list,
                                    include_writebacks=not program._for_test)
            program._exec_cache[key] = comp

        rng_key = rnd.default_generator().next_key()
        prev_rec = dispatch.set_graph_recorder(None)
        try:
            fetches = comp.run(feed_vals, rng_key)
        finally:
            dispatch.set_graph_recorder(prev_rec)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def close(self):
        pass


class CompiledProgramWrapper:
    """paddle.static.CompiledProgram parity shim."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def __getattr__(self, item):
        return getattr(self._program, item)


# =====================================================================
# Scope
# =====================================================================
class _VarView:
    def __init__(self, tensor: Tensor):
        self._t = tensor

    def get_tensor(self):
        return self._t.numpy()

    def set(self, value, place=None):
        self._t._value = jnp.asarray(value, dtype=self._t._value.dtype)


class Scope:
    """Name → persistable tensor view (reference: framework/scope.h:78)."""

    def __init__(self):
        self._extra: Dict[str, Tensor] = {}

    def find_var(self, name):
        for prog in filter(None, [_state().main_program]):
            for t, _ in prog._startup_actions:
                if getattr(t, "name", None) == name:
                    return _VarView(t)
        t = self._extra.get(name)
        return _VarView(t) if t is not None else None

    def var(self, name):
        if name not in self._extra:
            self._extra[name] = Tensor(jnp.zeros(()))
        return _VarView(self._extra[name])


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    yield


# =====================================================================
# Parameters in static mode
# =====================================================================
def create_parameter(shape, dtype, name=None, initializer=None,
                     is_bias=False, attr=None, trainable=True) -> Parameter:
    """Create an eager Parameter + record its initializer into the startup
    program (so Executor.run(startup_program) re-initializes, as the
    reference's startup program does)."""
    from ..nn import initializer as I

    if initializer is None:
        initializer = I.Constant(0.0) if is_bias else I.XavierUniform()
    prog = default_startup_program()
    name = name or default_main_program()._unique_name("param")
    shape = tuple(int(s) for s in shape)
    npdt = to_np(dtype)

    def init_fn():
        with pause_recording(), dispatch.no_grad_ctx():
            p = Parameter(jnp.zeros(shape, npdt), name=name)
            initializer(p)
            return p._value

    p = Parameter(init_fn(), name=name, trainable=trainable)
    prog._startup_actions.append((p, init_fn))
    default_main_program()._startup_actions.append((p, init_fn))
    return p


# =====================================================================
# save / load inference model
# =====================================================================
def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Export feed→fetch as serialized StableHLO + weights (reference:
    static.save_inference_model → program + persistables)."""
    import pickle

    program = program or default_main_program()
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    comp = _CompiledProgram(program, [v.name for v in feed_vars], fetch_vars,
                            include_writebacks=False)
    const_vals = [t._value for t in comp.consts]
    dyn_vals = [jnp.asarray(p()) for p in comp.dyns]

    def pure(*feed_vals):
        fetches, _ = comp._jitted.__wrapped__(
            list(feed_vals), const_vals, dyn_vals, jax.random.PRNGKey(0))
        return fetches

    # dims declared None/-1 export as symbolic (batch-size-agnostic serving)
    scope = jax.export.SymbolicScope()
    specs = []
    for i, v in enumerate(feed_vars):
        decl = v.declared_shape if v.declared_shape is not None else v.shape
        if any(d is None or d < 0 for d in decl):
            dim_str = ",".join(
                f"d{i}_{j}" if (d is None or d < 0) else str(d)
                for j, d in enumerate(decl))
            shape = jax.export.symbolic_shape(dim_str, scope=scope)
        else:
            shape = tuple(int(d) for d in decl)
        specs.append(jax.ShapeDtypeStruct(shape, v._value.dtype))
    exported = jax.export.export(jax.jit(pure))(*specs)
    blob = {
        "stablehlo": exported.serialize(),
        "feed_names": [v.name for v in feed_vars],
        "fetch_names": [getattr(v, "name", str(v)) for v in fetch_vars],
    }
    fname = path_prefix + ".pdmodel"
    with open(fname, "wb") as f:
        pickle.dump(blob, f, protocol=4)
    return fname


class LoadedInferenceProgram:
    def __init__(self, exported, feed_names, fetch_names):
        self._exported = exported
        self.feed_names = feed_names
        self.fetch_names = fetch_names

    def run(self, feed: Dict[str, Any]):
        vals = [jnp.asarray(feed[n]) for n in self.feed_names]
        return [np.asarray(o) for o in self._exported.call(*vals)]


def load_inference_model(path_prefix, executor=None, **kwargs):
    import pickle

    fname = (path_prefix if path_prefix.endswith(".pdmodel")
             else path_prefix + ".pdmodel")
    with open(fname, "rb") as f:
        blob = pickle.load(f)
    exported = jax.export.deserialize(blob["stablehlo"])
    prog = LoadedInferenceProgram(exported, blob["feed_names"],
                                  blob["fetch_names"])
    return [prog, prog.feed_names, prog.fetch_names]
