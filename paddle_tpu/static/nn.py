"""paddle.static.nn: graph-building layer helpers.

Reference: /root/reference/python/paddle/static/nn/__init__.py re-exporting
fluid.layers (fc, conv2d, batch_norm, embedding — fluid/layers/nn.py) and
control flow (fluid/layers/control_flow.py cond:?, while_loop:1167, case,
switch_case).  Here each helper creates eager Parameters (recorded into the
startup program) and calls the SAME functional ops as dygraph — the op
recording in static/graph.py turns them into program ops.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.dtype import to_np
from ..nn import functional as F
from ..nn import initializer as I
from . import graph as G


def _param(shape, dtype, attr, is_bias=False, default=None):
    """Create a parameter from a weight_attr that may be a ParamAttr, an
    initializer callable, or None."""
    from ..nn.layer.layers import ParamAttr

    name, trainable, init = None, True, None
    if isinstance(attr, ParamAttr):
        name, init, trainable = attr.name, attr.initializer, attr.trainable
    elif attr is not None:
        init = attr
    if init is None:
        init = default or (I.Constant(0.0) if is_bias else I.XavierUniform())
    return G.create_parameter(shape, dtype, name=name, initializer=init,
                              is_bias=is_bias, trainable=trainable)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """fluid.layers.fc analog (reference: fluid/layers/nn.py fc)."""
    in_dim = 1
    for d in x.shape[num_flatten_dims:]:
        in_dim *= int(d)
    w = _param([in_dim, size], x._value.dtype, weight_attr)
    b = None
    if bias_attr is not False:
        b = _param([size], x._value.dtype, bias_attr, is_bias=True)
    from .. import ops

    if len(x.shape) > num_flatten_dims + 1:
        # flatten uses runtime shapes — keeps the program batch-size-agnostic
        x = ops.flatten(x, start_axis=num_flatten_dims, stop_axis=-1)
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    w = _param(list(size), to_np(dtype), param_attr)
    return F.embedding(input, w, padding_idx=padding_idx)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    if isinstance(filter_size, int):
        filter_size = (filter_size, filter_size)
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    w = _param([num_filters, in_ch // groups, *filter_size],
               input._value.dtype, param_attr)
    b = None
    if bias_attr is not False:
        b = _param([num_filters], input._value.dtype, bias_attr,
                   is_bias=True)
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False,
               use_global_stats=False, name=None):
    from ..core.tensor import Tensor

    ch = int(input.shape[1 if data_layout == "NCHW" else -1])
    w = _param([ch], input._value.dtype, param_attr,
               default=I.Constant(1.0))
    b = _param([ch], input._value.dtype, bias_attr, is_bias=True)
    rm = Tensor(jnp.zeros([ch], input._value.dtype))
    rv = Tensor(jnp.ones([ch], input._value.dtype))
    rm.persistable = rv.persistable = True
    rm.stop_gradient = rv.stop_gradient = True
    out = F.batch_norm(input, rm, rv, w, b, training=not is_test,
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout,
                       use_global_stats=use_global_stats)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = [int(d) for d in input.shape[begin_norm_axis:]]
    w = _param(shape, input._value.dtype, param_attr,
               default=I.Constant(1.0)) if scale else None
    b = _param(shape, input._value.dtype, bias_attr,
               is_bias=True) if shift else None
    out = F.layer_norm(input, shape, w, b, epsilon)
    if act:
        out = getattr(F, act)(out)
    return out


def dropout(x, dropout_prob=0.5, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    mode = ("upscale_in_train"
            if dropout_implementation == "upscale_in_train"
            else "downscale_in_infer")
    return F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


# ------------------------------------------------------------ control flow
cond = G.static_cond
while_loop = G.static_while_loop


def case(pred_fn_pairs, default=None, name=None):
    """Chained conditionals (reference: fluid/layers/control_flow.py case):
    first pair whose pred is true wins; lowered to nested XLA conds."""
    pairs = list(pred_fn_pairs)
    if default is None:
        default = pairs[-1][1]

    def build(k):
        if k == len(pairs):
            return default()
        pred, fn = pairs[k]
        return G.static_cond(pred, fn, lambda: build(k + 1))

    return build(0)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Index dispatch (reference: control_flow.py switch_case)."""
    from .. import ops

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and callable(branch_fns[0]):
        items = list(enumerate(branch_fns))
    else:
        items = sorted(branch_fns)
    if default is None:
        default = items[-1][1]

    def build(k):
        if k == len(items):
            return default()
        idx, fn = items[k]
        return G.static_cond(ops.equal(branch_index, idx), fn,
                             lambda: build(k + 1))

    return build(0)


# ---------------------------------------------------------------------------
# conv / norm family (reference: python/paddle/static/nn/__init__.py
# re-exporting fluid.layers.*; each creates params then calls the same
# functional op the dygraph layer uses)
# ---------------------------------------------------------------------------

def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    """reference: fluid/layers/nn.py conv2d_transpose."""
    if filter_size is None:
        raise ValueError(
            "filter_size must be given (output_size-driven kernel "
            "inference is not supported; pass the kernel explicitly)")
    if isinstance(filter_size, int):
        filter_size = (filter_size, filter_size)
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    # transpose-conv weight layout: [in_channels, out_channels/groups, *k]
    w = _param([in_ch, num_filters // groups, *filter_size],
               input._value.dtype, param_attr)
    b = None
    if bias_attr is not False:
        b = _param([num_filters], input._value.dtype, bias_attr,
                   is_bias=True)
    out = F.conv2d_transpose(input, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size,
                             data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    """reference: fluid/layers/nn.py conv3d."""
    if isinstance(filter_size, int):
        filter_size = (filter_size,) * 3
    in_ch = int(input.shape[1 if data_format == "NCDHW" else -1])
    w = _param([num_filters, in_ch // groups, *filter_size],
               input._value.dtype, param_attr)
    b = None
    if bias_attr is not False:
        b = _param([num_filters], input._value.dtype, bias_attr,
                   is_bias=True)
    out = F.conv3d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    """reference: fluid/layers/nn.py conv3d_transpose."""
    if filter_size is None:
        raise ValueError("filter_size must be given")
    if isinstance(filter_size, int):
        filter_size = (filter_size,) * 3
    in_ch = int(input.shape[1 if data_format == "NCDHW" else -1])
    w = _param([in_ch, num_filters // groups, *filter_size],
               input._value.dtype, param_attr)
    b = None
    if bias_attr is not False:
        b = _param([num_filters], input._value.dtype, bias_attr,
                   is_bias=True)
    out = F.conv3d_transpose(input, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size,
                             data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    """reference: fluid/layers/nn.py group_norm."""
    ch = int(input.shape[1 if data_layout == "NCHW" else -1])
    w = _param([ch], input._value.dtype, param_attr,
               default=I.Constant(1.0)) if param_attr is not False else None
    b = _param([ch], input._value.dtype, bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    """reference: fluid/layers/nn.py instance_norm."""
    ch = int(input.shape[1])
    w = _param([ch], input._value.dtype, param_attr,
               default=I.Constant(1.0)) if param_attr is not False else None
    b = _param([ch], input._value.dtype, bias_attr, is_bias=True) \
        if bias_attr is not False else None
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    """reference: fluid/layers/nn.py prelu — mode selects the alpha shape:
    'all' one scalar, 'channel' per-channel, 'element' per-element."""
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [int(x.shape[1 if data_format == "NCHW" else -1])]
    elif mode == "element":
        shape = [1] + [int(d) for d in x.shape[1:]]
    else:
        raise ValueError("mode must be one of 'all', 'channel', 'element'")
    alpha = _param(shape, x._value.dtype, param_attr,
                   default=I.Constant(0.25))
    return F.prelu(x, alpha, data_format=data_format)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: fluid/layers/nn.py spectral_norm — creates the u/v power
    iteration vectors as non-trainable params."""
    import numpy as np

    h = int(weight.shape[dim])
    w_dim = int(np.prod([int(d) for i, d in enumerate(weight.shape)
                         if i != dim]))
    u = _param([h], weight._value.dtype, None, default=I.Normal(0.0, 1.0))
    v = _param([w_dim], weight._value.dtype, None, default=I.Normal(0.0, 1.0))
    u.stop_gradient = True
    v.stop_gradient = True
    return F.spectral_norm(weight, u, v, dim=dim, power_iters=power_iters,
                           eps=eps)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  modulated=True, name=None):
    """reference: fluid/layers/nn.py deformable_conv (static.nn
    deform_conv2d) — delegates to the vision op with created params."""
    from ..vision.ops import deform_conv2d as _dc

    if isinstance(filter_size, int):
        filter_size = (filter_size, filter_size)
    in_ch = int(input.shape[1])
    w = _param([num_filters, in_ch // groups, *filter_size],
               input._value.dtype, param_attr)
    b = None
    if bias_attr is not False:
        b = _param([num_filters], input._value.dtype, bias_attr,
                   is_bias=True)
    if not modulated:
        mask = None
    return _dc(input, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    """reference: fluid/layers/nn.py bilinear_tensor_product —
    out_k = x W_k y^T + b."""
    d1, d2 = int(x.shape[-1]), int(y.shape[-1])
    w = _param([size, d1, d2], x._value.dtype, param_attr)
    b = None
    if bias_attr is not False:
        b = _param([size], x._value.dtype, bias_attr, is_bias=True)
    out = F.bilinear(x, y, w, b)
    if act:
        out = getattr(F, act)(out)
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """reference: fluid/layers/nn.py data_norm (the pslib CTR
    normalization): running batch_size/batch_sum/batch_square_sum stats,
    out = (x - batch_sum/batch_size) * sqrt(batch_size/batch_square_sum).
    Stats start at the reference defaults (1e4 virtual samples) and
    ACCUMULATE each training forward (the reference does this in the
    data_norm grad kernel; here it is a writeback op on the program /
    an eager in-place update when grads are recording)."""
    from ..core.tensor import Tensor

    ch = int(input.shape[-1])
    dt = input._value.dtype
    bsz = _param([ch], dt, None, default=I.Constant(1e4))
    bsum = _param([ch], dt, None, default=I.Constant(0.0))
    bsq = _param([ch], dt, None, default=I.Constant(1e4))
    for p in (bsz, bsum, bsq):
        p.stop_gradient = True

    def _fn(v, size, s, sq):
        mean = s / size
        scale = jnp.sqrt(size / jnp.maximum(sq, epsilon))
        return (v - mean) * scale

    from ..core import dispatch
    from ..core.dispatch import apply

    out = apply("data_norm", _fn, input, bsz, bsum, bsq)

    def _accum(v, size, s, sq):
        n = float(v.shape[0])
        return (size + n, s + jnp.sum(v, 0), sq + jnp.sum(v * v, 0))

    if isinstance(input, G.Variable):
        G.record_writeback_op("data_norm_stats", _accum,
                              [input, bsz, bsum, bsq], [bsz, bsum, bsq])
    elif dispatch.is_grad_enabled():
        with dispatch.no_grad_ctx():
            nsz, nsum, nsq = _accum(input._value, bsz._value, bsum._value,
                                    bsq._value)
            bsz._value, bsum._value, bsq._value = nsz, nsum, nsq
    if enable_scale_and_shift:
        scale_w = _param([ch], dt, param_attr, default=I.Constant(1.0))
        bias = _param([ch], dt, None, is_bias=True)
        out = out * scale_w + bias
    if act:
        out = getattr(F, act)(out)
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference: fluid/layers/nn.py row_conv — lookahead convolution:
    out[t] = sum_{i=0..k} x[t+i] * w[i], per channel (DeepSpeech2's
    streaming-friendly context layer)."""
    d = int(input.shape[-1])
    k = int(future_context_size)
    w = _param([k + 1, d], input._value.dtype, param_attr)

    def _fn(v, wt):
        # v: [B, T, D]; shift-and-accumulate stays one fused XLA loop
        out = v * wt[0]
        for i in range(1, k + 1):
            shifted = jnp.concatenate(
                [v[:, i:, :], jnp.zeros_like(v[:, :i, :])], axis=1)
            out = out + shifted * wt[i]
        return out

    from ..core.dispatch import apply

    out = apply("row_conv", _fn, input, w)
    if act:
        out = getattr(F, act)(out)
    return out


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """reference: fluid/contrib/layers/sparse_embedding (PS giant-table
    embedding).  TPU-native: the table is an ordinary (GSPMD-shardable)
    parameter — 'sparse' admission/eviction policy objects (entry=...)
    are recorded on the parameter for checkpoint tooling but rows are
    dense in HBM; shard the vocab axis for >HBM tables."""
    w = _param(list(size), to_np(dtype), param_attr)
    if entry is not None:
        w._entry_attr = getattr(entry, "_to_attr", lambda: str(entry))()
    return F.embedding(input, w, padding_idx=padding_idx, sparse=True)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """reference: fluid/layers/nn.py nce — noise-contrastive estimation
    loss with a uniform/custom negative sampler.  Returns per-example
    loss [B, 1]."""
    import numpy as np

    from ..core.dispatch import apply
    from ..ops import random as rnd

    d = int(input.shape[-1])
    w = _param([num_total_classes, d], input._value.dtype, param_attr)
    b = _param([num_total_classes], input._value.dtype, bias_attr,
               is_bias=True) if bias_attr is not False else None
    if sampler not in ("uniform", "log_uniform", "custom_dist"):
        raise ValueError(f"unknown sampler {sampler!r}")
    if sampler == "custom_dist" and custom_dist is None:
        raise ValueError("custom_dist required for sampler='custom_dist'")
    key = rnd.next_key()
    s = int(num_neg_samples)

    import jax

    if sampler == "uniform":
        neg = jax.random.randint(key, (s,), 0, num_total_classes)
        logq = jnp.full((s,), -jnp.log(float(num_total_classes)))
        pos_logq = -jnp.log(float(num_total_classes))
    elif sampler == "log_uniform":
        # P(k) ∝ log((k+2)/(k+1)) — the reference's LogUniformSampler
        ks = np.arange(num_total_classes)
        p = np.log((ks + 2) / (ks + 1))
        p /= p.sum()
        neg = jax.random.choice(key, num_total_classes, (s,), p=jnp.asarray(p))
        logq = jnp.log(jnp.asarray(p)[neg])
        pos_logq = None  # gathered per-label below
        logp_table = jnp.asarray(np.log(p))
    else:
        p = np.asarray(custom_dist, np.float64)
        p /= p.sum()
        neg = jax.random.choice(key, num_total_classes, (s,), p=jnp.asarray(p))
        logq = jnp.log(jnp.asarray(p)[neg])
        pos_logq = None
        logp_table = jnp.asarray(np.log(p))

    def _fn(v, lab, wt, *maybe_b):
        bias = maybe_b[0] if maybe_b else None
        lab1 = lab.reshape(-1)
        pos_w = wt[lab1]                       # [B, D]
        pos_logit = jnp.sum(v * pos_w, -1)
        neg_logit = v @ wt[neg].T              # [B, S]
        if bias is not None:
            pos_logit = pos_logit + bias[lab1]
            neg_logit = neg_logit + bias[neg]
        plq = pos_logq if pos_logq is not None else logp_table[lab1]
        # NCE logistic objective (Gutmann & Hyvarinen): subtract log(S*q)
        pos_score = pos_logit - (jnp.log(float(s)) + plq)
        neg_score = neg_logit - (jnp.log(float(s)) + logq)
        loss = (jax.nn.softplus(-pos_score)
                + jnp.sum(jax.nn.softplus(neg_score), -1))
        return loss.reshape(-1, 1)

    args = [input, label, w] + ([b] if b is not None else [])
    return apply("nce", _fn, *args)


def crf_decoding(input, param_attr, label=None, length=None, name=None):
    """reference: fluid/layers/nn.py crf_decoding — Viterbi over emissions
    with the linear_chain_crf transition layout ([num_tags+2, num_tags]:
    row 0 start scores, row 1 stop scores, rows 2.. the transition
    matrix).  Returns the argmax tag path [B, T] (padded region zeros);
    with `label` given, returns the per-position correctness mask like
    the reference."""
    import jax

    from ..core.dispatch import apply

    num_tags = int(input.shape[-1])
    trans = _param([num_tags + 2, num_tags], input._value.dtype, param_attr)

    def _fn(em, w, *rest):
        start, stop, t = w[0], w[1], w[2:]
        B, T, C = em.shape
        lens = rest[0].reshape(B).astype(jnp.int32) if length is not None \
            else jnp.full((B,), T, jnp.int32)
        lab = rest[-1] if label is not None else None

        def step(carry, e_t):
            alpha = carry
            sc = alpha[:, :, None] + t[None] + e_t[:, None, :]
            new = jnp.max(sc, 1)
            return new, (new, jnp.argmax(sc, 1))

        alpha0 = start[None] + em[:, 0]
        _, (alphas, back) = jax.lax.scan(
            step, alpha0, jnp.moveaxis(em[:, 1:], 1, 0))
        # alphas[t] is the score after consuming emission t+1
        all_alpha = jnp.concatenate([alpha0[None], alphas], 0)  # [T, B, C]
        final = jnp.take_along_axis(
            all_alpha, (lens - 1)[None, :, None], 0)[0] + stop[None]
        lastt = jnp.argmax(final, -1)

        def walk(cur, xs):
            t_idx, bp_t = xs
            prev = jnp.take_along_axis(bp_t, cur[:, None], 1)[:, 0]
            nxt = jnp.where(t_idx == lens - 1, lastt,
                            jnp.where(t_idx < lens - 1, prev, 0))
            return nxt, nxt

        ts = jnp.arange(T - 2, -1, -1)
        _, path_rev = jax.lax.scan(walk, lastt, (ts, back[::-1]))
        tail = jnp.where(lens - 1 == T - 1, lastt, 0)
        path = jnp.concatenate([path_rev[::-1].T, tail[:, None]], 1)
        path = jnp.where(jnp.arange(T)[None] < lens[:, None], path, 0)
        if lab is not None:  # label -> correctness mask, ref semantics
            return (path == lab.reshape(B, T)).astype(em.dtype)
        return path

    extra = [x for x in (length, label) if x is not None]
    return apply("crf_decoding", _fn, input, trans, *extra)


# ---------------------------------------------------------------------------
# sequence ops (reference: python/paddle/fluid/layers/sequence_lod.py)
#
# LoD redesign: the reference threads ragged sequences through ops as
# LoDTensors (flat rows + offset table — a dynamic shape XLA cannot
# compile).  TPU-native, a ragged batch is the pair the reference's OWN
# sequence_pad/sequence_unpad convert to and from: padded [B, T, ...] plus
# lengths [B].  sequence_pad attaches the lengths to the padded Tensor
# (attr `_seq_lengths`); every sequence_* op reads them (default: full
# length) and propagates them, so reference pipelines compose unchanged
# between pad/unpad endpoints.  Static shapes throughout — the padded
# time axis is the compile-time bound.
# ---------------------------------------------------------------------------

def _seq_lens(x, default_T=None):
    lens = getattr(x, "_seq_lengths", None)
    if lens is not None:
        return lens._value if hasattr(lens, "_value") else jnp.asarray(lens)
    T = default_T if default_T is not None else int(x.shape[1])
    return jnp.full((int(x.shape[0]),), T, jnp.int32)


def _with_lens(out, lens):
    from ..core.tensor import Tensor

    if not isinstance(lens, Tensor):
        lens = Tensor(jnp.asarray(lens, jnp.int32), stop_gradient=True)
    out._seq_lengths = lens
    return out


def _time_mask(x_val, lens, upto=None):
    T = upto if upto is not None else x_val.shape[1]
    return jnp.arange(T)[None, :] < lens[:, None]


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """reference: sequence_lod.py sequence_pad — ragged in, (padded,
    lengths) out.  Accepts a list of per-sequence Tensors/arrays (the
    ragged form) or an already-padded Tensor (passthrough + lengths)."""
    import numpy as np

    from ..core.tensor import Tensor

    pv = float(pad_value if not hasattr(pad_value, "numpy")
               else pad_value.numpy())
    if isinstance(x, (list, tuple)):
        rows = [r._value if isinstance(r, Tensor) else jnp.asarray(r)
                for r in x]
        T = maxlen or max(int(r.shape[0]) for r in rows)
        # truncation must also truncate the REPORTED length — every
        # sequence op masks with it, so a stale length corrupts pooling,
        # softmax, conv, ... downstream
        lens = [min(int(r.shape[0]), T) for r in rows]
        feat = rows[0].shape[1:]
        out = jnp.full((len(rows), T) + tuple(feat), pv, rows[0].dtype)
        for i, r in enumerate(rows):
            out = out.at[i, :lens[i]].set(r[:lens[i]])
        padded = Tensor(out)
        lens_t = Tensor(jnp.asarray(lens, jnp.int32), stop_gradient=True)
        _with_lens(padded, lens_t)
        return padded, lens_t
    lens = _seq_lens(x)
    out = Tensor(jnp.where(_time_mask(x._value, lens)[
        (...,) + (None,) * (x._value.ndim - 2)], x._value, pv)) \
        if x._value.ndim > 2 else Tensor(
            jnp.where(_time_mask(x._value, lens), x._value, pv))
    lens_t = Tensor(lens, stop_gradient=True)
    _with_lens(out, lens_t)
    return out, lens_t


def sequence_unpad(x, length, name=None):
    """reference: sequence_lod.py sequence_unpad — back to ragged: a list
    of [len_i, ...] Tensors."""
    from ..core.tensor import Tensor

    lens = length._value if hasattr(length, "_value") else \
        jnp.asarray(length)
    return [Tensor(x._value[i, :int(lens[i])])
            for i in range(int(x.shape[0]))]


def sequence_softmax(input, use_cudnn=False, name=None):
    """softmax over each sequence's valid steps (reference
    sequence_softmax); padded positions get zero probability."""
    from ..core.dispatch import apply

    lens = _seq_lens(input)

    def _fn(v):
        mask = _time_mask(v, lens)
        if v.ndim > 2:
            mask = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        shifted = jnp.where(mask, v, -jnp.inf)
        e = jnp.exp(shifted - jnp.max(shifted, 1, keepdims=True))
        e = jnp.where(mask, e, 0.0)
        return e / jnp.maximum(jnp.sum(e, 1, keepdims=True), 1e-12)

    out = apply("sequence_softmax", _fn, input)
    return _with_lens(out, lens)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    """reference: sequence_lod.py sequence_pool — masked reduction over
    the time axis; empty sequences emit pad_value."""
    from ..core.dispatch import apply

    lens = _seq_lens(input)
    kind = pool_type.lower()

    def _fn(v):
        mask = _time_mask(v, lens)
        m = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        n = jnp.maximum(lens, 1).reshape((-1,) + (1,) * (v.ndim - 2))
        if kind == "sum":
            out = jnp.sum(jnp.where(m, v, 0), 1)
        elif kind == "average":
            out = jnp.sum(jnp.where(m, v, 0), 1) / n
        elif kind == "sqrt":
            out = jnp.sum(jnp.where(m, v, 0), 1) / jnp.sqrt(
                n.astype(v.dtype))
        elif kind == "max":
            out = jnp.max(jnp.where(m, v, -jnp.inf), 1)
        elif kind == "first":
            out = v[:, 0]
        elif kind == "last":
            idx = jnp.maximum(lens - 1, 0)
            out = jnp.take_along_axis(
                v, idx.reshape((-1, 1) + (1,) * (v.ndim - 2)), 1)[:, 0]
        else:
            raise ValueError(f"unknown pool_type {pool_type!r}")
        empty = (lens == 0).reshape((-1,) + (1,) * (v.ndim - 2))
        return jnp.where(empty, pad_value, out)

    return apply("sequence_pool", _fn, input)


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """reference: sequence_lod.py sequence_conv — context-window linear:
    each step's features are the concat of `filter_size` neighbor rows
    (window starting at padding_start, default centered), then a dense
    projection.  Zero rows outside [0, len)."""
    from ..core.dispatch import apply

    if filter_stride != 1:
        raise ValueError("sequence_conv supports filter_stride=1 "
                         "(reference kernel limitation as well)")
    d = int(input.shape[-1])
    k = int(filter_size)
    start = padding_start if padding_start is not None else -((k - 1) // 2)
    w = _param([k * d, num_filters], input._value.dtype, param_attr)
    b = None
    if bias_attr is not False:
        b = _param([num_filters], input._value.dtype, bias_attr,
                   is_bias=True)
    lens = _seq_lens(input)

    def _fn(v, wt, *maybe_b):
        B, T, D = v.shape
        mask = _time_mask(v, lens)[..., None]
        vm = jnp.where(mask, v, 0)
        cols = []
        for i in range(k):
            off = start + i
            if off < 0:
                sh = jnp.concatenate(
                    [jnp.zeros((B, min(-off, T), D), v.dtype),
                     vm[:, :max(T + off, 0)]], 1)
            elif off > 0:
                sh = jnp.concatenate(
                    [vm[:, min(off, T):],
                     jnp.zeros((B, min(off, T), D), v.dtype)], 1)
            else:
                sh = vm
            cols.append(sh)
        ctx = jnp.concatenate(cols, -1)  # [B, T, k*D]
        out = ctx @ wt
        if maybe_b:
            out = out + maybe_b[0]
        return jnp.where(mask, out, 0)

    args = [input, w] + ([b] if b is not None else [])
    out = apply("sequence_conv", _fn, *args)
    if act:
        out = getattr(F, act)(out)
    return _with_lens(out, lens)


def sequence_concat(input, name=None):
    """reference: sequence_lod.py sequence_concat — per-ROW concatenation
    of the valid steps of each input (time-axis splice, not a plain
    concat: row i of the result is seq_i(x1) ++ seq_i(x2) ++ ...)."""
    from ..core.dispatch import apply

    xs = list(input)
    lens_list = [_seq_lens(x) for x in xs]
    total = sum(int(x.shape[1]) for x in xs)
    out_lens = sum(lens_list[1:], lens_list[0])

    def _fn(*vals):
        B = vals[0].shape[0]
        feat = vals[0].shape[2:]
        out = jnp.zeros((B, total) + tuple(feat), vals[0].dtype)
        offs = jnp.zeros((B,), jnp.int32)
        for v, ln in zip(vals, lens_list):
            T = v.shape[1]
            tpos = jnp.arange(T)[None, :]
            dest = offs[:, None] + tpos             # [B, T]
            valid = tpos < ln[:, None]
            dest = jnp.where(valid, dest, total)    # OOB rows drop
            bidx = jnp.broadcast_to(jnp.arange(B)[:, None], dest.shape)
            out = out.at[bidx.reshape(-1), dest.reshape(-1)].set(
                v.reshape((-1,) + tuple(feat)), mode="drop")
            offs = offs + ln
        return out

    out = apply("sequence_concat", _fn, *xs)
    return _with_lens(out, out_lens)


def sequence_slice(input, offset, length, name=None):
    """reference: sequence_lod.py sequence_slice — per-sequence
    [offset, offset+length) window."""
    from ..core.dispatch import apply

    off = (offset._value if hasattr(offset, "_value")
           else jnp.asarray(offset)).reshape(-1)
    ln = (length._value if hasattr(length, "_value")
          else jnp.asarray(length)).reshape(-1)
    T_out = int(jnp.max(ln))

    def _fn(v):
        tpos = jnp.arange(T_out)[None, :]
        src = off[:, None] + tpos
        src = jnp.clip(src, 0, v.shape[1] - 1)
        idx = src.reshape((v.shape[0], T_out) + (1,) * (v.ndim - 2))
        out = jnp.take_along_axis(v, idx, 1)
        mask = (tpos < ln[:, None]).reshape(
            (v.shape[0], T_out) + (1,) * (v.ndim - 2))
        return jnp.where(mask, out, 0)

    out = apply("sequence_slice", _fn, input)
    return _with_lens(out, ln.astype(jnp.int32))


def sequence_expand(x, y, ref_level=-1, name=None):
    """reference: sequence_lod.py sequence_expand — repeat each sequence
    of x per y's lod.  Padded-rep: supported for the dominant case where
    x holds ONE step per sequence (attention context / beam state); each
    row broadcasts across y's valid steps."""
    from ..core.dispatch import apply

    y_lens = _seq_lens(y)
    Ty = int(y.shape[1])
    xv_ndim = len(x.shape)
    if xv_ndim >= 3 and int(x.shape[1]) != 1:
        raise NotImplementedError(
            "sequence_expand on multi-step x requires ragged LoD "
            "semantics; broadcast a one-step x or use sequence_expand_as")

    def _fn(xv):
        v = xv if xv.ndim >= 3 else xv[:, None]
        out = jnp.broadcast_to(v, (v.shape[0], Ty) + v.shape[2:])
        mask = _time_mask(out, y_lens).reshape(
            (v.shape[0], Ty) + (1,) * (out.ndim - 2))
        return jnp.where(mask, out, 0)

    out = apply("sequence_expand", _fn, x)
    return _with_lens(out, y_lens)


def sequence_expand_as(x, y, name=None):
    """reference: sequence_lod.py sequence_expand_as (ref_level 0)."""
    return sequence_expand(x, y, ref_level=0, name=name)


def sequence_reshape(input, new_dim):
    """reference: sequence_lod.py sequence_reshape — refold each
    sequence's (len_i * D) values into rows of new_dim."""
    from ..core.dispatch import apply
    from ..core.tensor import Tensor

    d = int(input.shape[-1])
    lens = _seq_lens(input)
    T = int(input.shape[1])
    if (T * d) % new_dim:
        raise ValueError(
            f"sequence_reshape: T*D={T * d} not divisible by {new_dim}")
    T_out = T * d // new_dim
    new_lens = (lens * d) // new_dim

    def _fn(v):
        B = v.shape[0]
        flat = jnp.where(_time_mask(v, lens)[..., None], v, 0)
        return flat.reshape(B, T_out, new_dim)

    out = apply("sequence_reshape", _fn, input)
    return _with_lens(out, new_lens)


def sequence_scatter(input, index, updates, name=None):
    """reference: sequence_lod.py sequence_scatter — add `updates` into
    `input` at each sequence's `index` time-positions."""
    from ..core.dispatch import apply

    idx_lens = _seq_lens(index)

    def _fn(v, idx, upd):
        B = v.shape[0]
        ii = idx.reshape(B, -1).astype(jnp.int32)
        uu = upd.reshape(B, ii.shape[1])
        valid = jnp.arange(ii.shape[1])[None, :] < idx_lens[:, None]
        ii = jnp.where(valid, ii, v.shape[1])  # OOB -> dropped
        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], ii.shape)
        return v.at[bidx.reshape(-1), ii.reshape(-1)].add(
            uu.reshape(-1), mode="drop")

    return apply("sequence_scatter", _fn, input, index, updates)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """reference: sequence_lod.py sequence_enumerate — sliding windows of
    ids: out[b, t] = [x[t], x[t+1], ..., x[t+w-1]], pad past the end."""
    from ..core.dispatch import apply

    lens = _seq_lens(input)

    def _fn(v):
        B, T = v.shape[:2]
        tpos = jnp.arange(T)[None, :, None]
        offs = jnp.arange(win_size)[None, None, :]
        src = tpos + offs                            # [1, T, W]
        gather = jnp.take_along_axis(
            v[:, :, None] if v.ndim == 2 else v,
            jnp.broadcast_to(jnp.minimum(src, T - 1), (B, T, win_size)), 1)
        valid = src < lens[:, None, None]
        return jnp.where(valid, gather, pad_value)

    out = apply("sequence_enumerate", _fn, input)
    return _with_lens(out, lens)


def sequence_reverse(x, name=None):
    """reference: sequence_lod.py sequence_reverse — reverse each valid
    region in place, keep padding at the tail."""
    from ..core.dispatch import apply

    lens = _seq_lens(x)

    def _fn(v):
        B, T = v.shape[0], v.shape[1]
        tpos = jnp.arange(T)[None, :]
        src = jnp.where(tpos < lens[:, None], lens[:, None] - 1 - tpos, tpos)
        idx = src.reshape((B, T) + (1,) * (v.ndim - 2))
        return jnp.take_along_axis(v, idx, 1)

    out = apply("sequence_reverse", _fn, x)
    return _with_lens(out, lens)


# ---------------------------------------------------------------------------
# py_func / multi_box_head
# ---------------------------------------------------------------------------

def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None,
            name=None):
    """reference: fluid/layers/nn.py py_func — run a host Python function
    as an op.  Eagerly the callback runs on numpy views directly; under a
    jit trace it lowers to jax.pure_callback with `out`'s shape/dtype as
    the result signature (host round trip — same data movement as the
    reference's CPU-pinned py_func op).  backward_func, when given,
    becomes the custom VJP and receives the REFERENCE CONTRACT
    (fluid/layers/nn.py py_func_demo): positional args are
    (inputs..., outputs..., output_grads...), minus any variable listed
    in skip_vars_in_backward_input; it returns one gradient per input."""
    import numpy as np

    import jax

    from ..core.dispatch import apply
    from ..core.tensor import Tensor

    xs = [x] if isinstance(x, Tensor) else list(x)
    outs = [out] if not isinstance(out, (list, tuple)) else list(out)
    single = not isinstance(out, (list, tuple))
    shape_dtypes = [jax.ShapeDtypeStruct(
        tuple(int(d) for d in o.shape), o._value.dtype) for o in outs]

    def _host(*vals):
        res = func(*[np.asarray(v) for v in vals])
        res = res if isinstance(res, (list, tuple)) else [res]
        return [np.asarray(r._value if isinstance(r, Tensor) else r,
                           sd.dtype).reshape(sd.shape)
                for r, sd in zip(res, shape_dtypes)]

    def _fn(*vals):
        if any(isinstance(v, jax.core.Tracer) for v in vals):
            res = jax.pure_callback(
                lambda *a: tuple(_host(*a)), tuple(shape_dtypes), *vals)
        else:
            res = tuple(jnp.asarray(r) for r in _host(*vals))
        return res[0] if single else tuple(res)

    if backward_func is not None:
        n_in = len(xs)
        # the reference identifies skipped vars by Variable identity/name;
        # here positions: inputs occupy [0, n_in), outputs [n_in, n_in+n_out)
        skip_ids = {id(v) for v in (skip_vars_in_backward_input or [])}
        skip_pos = set()
        for pos, v in enumerate(xs + outs):
            if id(v) in skip_ids:
                skip_pos.add(pos)

        @jax.custom_vjp
        def _op(*vals):
            return _fn(*vals)

        def _fwd(*vals):
            outs_v = _fn(*vals)
            flat_outs = (outs_v,) if single else tuple(outs_v)
            return outs_v, (vals, flat_outs)

        def _bwd(saved, ct):
            ins_v, outs_v = saved
            cts = (ct,) if single else tuple(ct)
            # reference arg order: inputs, outputs, output grads — with
            # skip_vars_in_backward_input removed from the first two groups
            bargs = [v for pos, v in enumerate(ins_v + outs_v)
                     if pos not in skip_pos] + list(cts)

            def _hostb(*a):
                res = backward_func(*[np.asarray(v) for v in a])
                res = res if isinstance(res, (list, tuple)) else [res]
                return [np.asarray(
                    r._value if isinstance(r, Tensor) else r).reshape(
                        ins_v[i].shape).astype(ins_v[i].dtype)
                    for i, r in enumerate(res)]

            in_sds = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in ins_v]
            grads = jax.pure_callback(
                lambda *a: tuple(_hostb(*a)), tuple(in_sds), *bargs)
            return tuple(grads[:n_in])

        _op.defvjp(_fwd, _bwd)
        result = apply("py_func", _op, *xs)
    else:
        result = apply("py_func", _fn, *xs, _differentiable=False)
    return result


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """reference: fluid/layers/detection.py multi_box_head — the SSD
    prediction head: per feature map, a loc conv (priors*4 channels), a
    conf conv (priors*num_classes), and the prior-box grid.  Returns
    (mbox_locs [B, P, 4], mbox_confs [B, P, C], boxes [P, 4],
    variances [P, 4])."""
    import math

    import numpy as np

    from .. import ops
    from ..core.tensor import Tensor

    n_maps = len(inputs)
    if min_sizes is None:
        # reference ratio schedule: evenly spaced between min/max ratio
        min_sizes, max_sizes = [], []
        step_r = int(math.floor(max_ratio - min_ratio) / (n_maps - 2)) \
            if n_maps > 2 else 0
        ratios = list(range(int(min_ratio), int(max_ratio) + 1,
                            max(step_r, 1)))
        min_sizes = [base_size * 0.10] + [base_size * r / 100.
                                          for r in ratios[:n_maps - 1]]
        max_sizes = [base_size * 0.20] + [base_size * (r + step_r) / 100.
                                          for r in ratios[:n_maps - 1]]
    img_h = int(image.shape[2])
    img_w = int(image.shape[3])

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        mins = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        maxs = (max_sizes[i] if isinstance(max_sizes[i], (list, tuple))
                else [max_sizes[i]]) if max_sizes else []
        ars = aspect_ratios[i] if isinstance(
            aspect_ratios[i], (list, tuple)) else [aspect_ratios[i]]
        full_ars = [1.0]
        for ar in ars:
            if ar != 1.0:
                full_ars.append(ar)
                if flip:
                    full_ars.append(1.0 / ar)
        if len(maxs) > len(mins):
            raise ValueError(
                f"max_sizes ({len(maxs)}) must pair 1:1 with min_sizes "
                f"({len(mins)})")
        num_priors = len(mins) * len(full_ars) + len(maxs)

        fh, fw = int(feat.shape[2]), int(feat.shape[3])
        sw = steps[i] if steps else (step_w[i] if step_w else img_w / fw)
        sh = steps[i] if steps else (step_h[i] if step_h else img_h / fh)
        # prior grid (host numpy: static per-graph constants)
        cx = (np.arange(fw) + offset) * sw
        cy = (np.arange(fh) + offset) * sh
        cxg, cyg = np.meshgrid(cx, cy)
        pri = []
        for j, m in enumerate(mins):
            for ar in full_ars:
                bw, bh = m * math.sqrt(ar) / 2, m / math.sqrt(ar) / 2
                pri.append((bw, bh))
            # max sizes pair 1:1 with min sizes (SSD prior_box contract);
            # a nested maxs loop would emit len(mins)*len(maxs) boxes and
            # overflow the num_priors channel budget above
            if j < len(maxs):
                s = math.sqrt(m * maxs[j]) / 2
                pri.append((s, s))
        grid = np.stack([cxg, cyg], -1).reshape(-1, 1, 2)  # [fh*fw, 1, 2]
        wh = np.asarray(pri).reshape(1, -1, 2)             # [1, P, 2]
        mins_xy = (grid - wh) / np.asarray([img_w, img_h])
        maxs_xy = (grid + wh) / np.asarray([img_w, img_h])
        box = np.concatenate([mins_xy, maxs_xy], -1).reshape(-1, 4)
        if clip:
            box = np.clip(box, 0.0, 1.0)
        boxes_all.append(box.astype(np.float32))
        vars_all.append(np.tile(np.asarray(variance, np.float32),
                                (box.shape[0], 1)))

        loc = conv2d(feat, num_priors * 4, kernel_size, stride=stride,
                     padding=pad)
        conf = conv2d(feat, num_priors * num_classes, kernel_size,
                      stride=stride, padding=pad)
        B = int(feat.shape[0])
        loc = ops.reshape(ops.transpose(loc, [0, 2, 3, 1]), [B, -1, 4])
        conf = ops.reshape(ops.transpose(conf, [0, 2, 3, 1]),
                           [B, -1, num_classes])
        locs.append(loc)
        confs.append(conf)

    mbox_locs = ops.concat(locs, axis=1)
    mbox_confs = ops.concat(confs, axis=1)
    boxes = Tensor(jnp.asarray(np.concatenate(boxes_all, 0)),
                   stop_gradient=True)
    variances = Tensor(jnp.asarray(np.concatenate(vars_all, 0)),
                       stop_gradient=True)
    return mbox_locs, mbox_confs, boxes, variances
