"""paddle.static.nn: graph-building layer helpers.

Reference: /root/reference/python/paddle/static/nn/__init__.py re-exporting
fluid.layers (fc, conv2d, batch_norm, embedding — fluid/layers/nn.py) and
control flow (fluid/layers/control_flow.py cond:?, while_loop:1167, case,
switch_case).  Here each helper creates eager Parameters (recorded into the
startup program) and calls the SAME functional ops as dygraph — the op
recording in static/graph.py turns them into program ops.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.dtype import to_np
from ..nn import functional as F
from ..nn import initializer as I
from . import graph as G


def _param(shape, dtype, attr, is_bias=False, default=None):
    """Create a parameter from a weight_attr that may be a ParamAttr, an
    initializer callable, or None."""
    from ..nn.layer.layers import ParamAttr

    name, trainable, init = None, True, None
    if isinstance(attr, ParamAttr):
        name, init, trainable = attr.name, attr.initializer, attr.trainable
    elif attr is not None:
        init = attr
    if init is None:
        init = default or (I.Constant(0.0) if is_bias else I.XavierNormal())
    return G.create_parameter(shape, dtype, name=name, initializer=init,
                              is_bias=is_bias, trainable=trainable)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """fluid.layers.fc analog (reference: fluid/layers/nn.py fc)."""
    in_dim = 1
    for d in x.shape[num_flatten_dims:]:
        in_dim *= int(d)
    w = _param([in_dim, size], x._value.dtype, weight_attr)
    b = None
    if bias_attr is not False:
        b = _param([size], x._value.dtype, bias_attr, is_bias=True)
    from .. import ops

    if len(x.shape) > num_flatten_dims + 1:
        # flatten uses runtime shapes — keeps the program batch-size-agnostic
        x = ops.flatten(x, start_axis=num_flatten_dims, stop_axis=-1)
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    w = _param(list(size), to_np(dtype), param_attr)
    return F.embedding(input, w, padding_idx=padding_idx)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    if isinstance(filter_size, int):
        filter_size = (filter_size, filter_size)
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    w = _param([num_filters, in_ch // groups, *filter_size],
               input._value.dtype, param_attr)
    b = None
    if bias_attr is not False:
        b = _param([num_filters], input._value.dtype, bias_attr,
                   is_bias=True)
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False,
               use_global_stats=False, name=None):
    from ..core.tensor import Tensor

    ch = int(input.shape[1 if data_layout == "NCHW" else -1])
    w = _param([ch], input._value.dtype, param_attr,
               default=I.Constant(1.0))
    b = _param([ch], input._value.dtype, bias_attr, is_bias=True)
    rm = Tensor(jnp.zeros([ch], input._value.dtype))
    rv = Tensor(jnp.ones([ch], input._value.dtype))
    rm.persistable = rv.persistable = True
    rm.stop_gradient = rv.stop_gradient = True
    out = F.batch_norm(input, rm, rv, w, b, training=not is_test,
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout,
                       use_global_stats=use_global_stats)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = [int(d) for d in input.shape[begin_norm_axis:]]
    w = _param(shape, input._value.dtype, param_attr,
               default=I.Constant(1.0)) if scale else None
    b = _param(shape, input._value.dtype, bias_attr,
               is_bias=True) if shift else None
    out = F.layer_norm(input, shape, w, b, epsilon)
    if act:
        out = getattr(F, act)(out)
    return out


def dropout(x, dropout_prob=0.5, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    mode = ("upscale_in_train"
            if dropout_implementation == "upscale_in_train"
            else "downscale_in_infer")
    return F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


# ------------------------------------------------------------ control flow
cond = G.static_cond
while_loop = G.static_while_loop


def case(pred_fn_pairs, default=None, name=None):
    """Chained conditionals (reference: fluid/layers/control_flow.py case):
    first pair whose pred is true wins; lowered to nested XLA conds."""
    pairs = list(pred_fn_pairs)
    if default is None:
        default = pairs[-1][1]

    def build(k):
        if k == len(pairs):
            return default()
        pred, fn = pairs[k]
        return G.static_cond(pred, fn, lambda: build(k + 1))

    return build(0)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Index dispatch (reference: control_flow.py switch_case)."""
    from .. import ops

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and callable(branch_fns[0]):
        items = list(enumerate(branch_fns))
    else:
        items = sorted(branch_fns)
    if default is None:
        default = items[-1][1]

    def build(k):
        if k == len(items):
            return default()
        idx, fn = items[k]
        return G.static_cond(ops.equal(branch_index, idx), fn,
                             lambda: build(k + 1))

    return build(0)
