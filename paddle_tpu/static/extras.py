"""Static-mode surface fills (reference: python/paddle/static/__init__.py
exports — strategies, EMA, program serialization, place lists, var
save/load).  Strategy objects are accepted-and-recorded shims: their
knobs configure executors/SSA passes in the reference, all of which XLA
owns here; they are kept so reference training scripts run unchanged.
"""
from __future__ import annotations

import pickle

import numpy as np


class BuildStrategy:
    """Reference: framework/details/build_strategy.h — graph-build knobs
    (fusion toggles, reduce strategy).  XLA performs the fusions; the
    object records settings for compatibility."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_bn_add_act_ops = True
        self.enable_auto_fusion = False
        self.fuse_relu_depthwise_conv = False
        self.sync_batch_norm = False
        self.memory_optimize = None
        self.enable_inplace = True
        self.build_cinn_pass = False

    def __repr__(self):
        return f"BuildStrategy({self.__dict__})"


class ExecutionStrategy:
    """Reference: ExecutionStrategy (num_threads, num_iteration_per_run)."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.num_iteration_per_run = 1
        self.use_thread_barrier = True


class ParallelExecutor:
    """Legacy multi-device executor facade (reference:
    framework/details ParallelExecutor; SURVEY declares it superseded by
    SPMD compilation).  Wraps the ordinary Executor: under GSPMD one
    compiled program spans all devices, which is this class's contract."""

    def __init__(self, use_cuda=None, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from .graph import Executor, default_main_program

        self._program = main_program or default_main_program()
        self._exe = Executor()
        self._loss_name = loss_name

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


class ExponentialMovingAverage:
    """EMA of parameters for evaluation (reference:
    python/paddle/static/__init__.py ExponentialMovingAverage over
    fluid/optimizer.py): update() folds current params into the shadow
    with bias correction; apply()/restore() swap them in and out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._shadow = {}
        self._backup = {}
        self._step = 0

    _tracked = []

    def track(self, parameters):
        """Eager-mode registration (dygraph path of the reference API)."""
        self._tracked = list(parameters)
        return self

    def update(self):
        import jax.numpy as jnp

        self._step += 1
        d = min(self._decay, (1.0 + self._step) / (10.0 + self._step))
        for p in self._tracked:
            prev = self._shadow.get(id(p))
            cur = p._value.astype(jnp.float32)
            self._shadow[id(p)] = cur if prev is None else \
                d * prev + (1.0 - d) * cur

    def apply(self, executor=None, need_restore=True):
        from contextlib import contextmanager

        self._backup = {id(p): p._value for p in self._tracked}
        for p in self._tracked:
            if id(p) in self._shadow:
                p._value = self._shadow[id(p)].astype(p._value.dtype)

        @contextmanager
        def ctx():
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def restore(self, executor=None):
        for p in self._tracked:
            if id(p) in self._backup:
                p._value = self._backup[id(p)]
        self._backup = {}


# ---------------------------------------------------------------------------
# program/persistable serialization (reference: static/io.py
# serialize_program:SerializeProgram etc.)
# ---------------------------------------------------------------------------

def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    from .graph import default_main_program, save_inference_model

    import io as _io
    import tempfile
    import os

    program = program or default_main_program()
    with tempfile.TemporaryDirectory() as d:
        save_inference_model(os.path.join(d, "m"), feed_vars, fetch_vars,
                             program=program)
        with open(os.path.join(d, "m.pdmodel"), "rb") as f:
            return f.read()


def deserialize_program(data):
    import pickle as _p

    return _p.loads(data)


def _persistables(program):
    """All live parameter tensors a program depends on: startup-action
    vars (static.create_parameter) plus Layer parameters captured as
    'const' op inputs (nn layers called under program_guard)."""
    seen = {}
    for t, _init in program._startup_actions:
        seen.setdefault(id(t), t)
    for block in program.blocks:
        for op in block.ops:
            for kind, ref in op.inputs:
                if kind == "const" and getattr(ref, "persistable", False):
                    seen.setdefault(id(ref), ref)
    out = {}
    for i, t in enumerate(seen.values()):
        out[t.name or f"param_{i}"] = t
    return out


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    from .graph import default_main_program

    program = program or default_main_program()
    state = {name: np.asarray(t._value)
             for name, t in _persistables(program).items()}
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    import jax.numpy as jnp

    for name, t in _persistables(program).items():
        if name in state:
            t._value = jnp.asarray(state[name])
    return state


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Prune to the feed->fetch slice (reference: static/io.py
    normalize_program).  Our Program already records exactly the traced
    slice; dead ops are removed via the pass framework."""
    from .passes import apply_pass

    names = [v.name for v in (fetch_vars if isinstance(fetch_vars, list)
                              else [fetch_vars])]
    apply_pass(program, "eliminate_dead_ops", keep=names)
    return program


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from .graph import default_main_program

    import os

    program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    state = {}
    for name, t in _persistables(program).items():
        if predicate is not None and not predicate(t):
            continue
        state[name] = np.asarray(t._value)
    out = os.path.join(dirname, filename or "vars.pkl")
    with open(out, "wb") as f:
        pickle.dump(state, f)
    return out


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from .graph import default_main_program

    import os
    import jax.numpy as jnp

    program = main_program or default_main_program()
    with open(os.path.join(dirname, filename or "vars.pkl"), "rb") as f:
        state = pickle.load(f)
    for name, t in _persistables(program).items():
        if name in state:
            t._value = jnp.asarray(state[name])


def load_program_state(model_path, var_list=None):
    import os

    path = model_path if model_path.endswith(".pkl") else \
        os.path.join(model_path, "vars.pkl")
    with open(path, "rb") as f:
        return pickle.load(f)


def set_program_state(program, state):
    import jax.numpy as jnp

    for name, t in _persistables(program).items():
        if name in state:
            t._value = jnp.asarray(state[name])


# ---------------------------------------------------------------------------
# places + misc
# ---------------------------------------------------------------------------

def cpu_places(device_count=None):
    from ..core.place import CPUPlace

    import os

    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places (TPU chips fill the CUDA position)."""
    import jax

    from ..core.place import CUDAPlace

    # a placement list is per-process: only local devices are
    # addressable under jax.distributed (H112)
    ids = device_ids if device_ids is not None else range(
        len(jax.local_devices()))
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def npu_places(device_ids=None):
    return cuda_places(device_ids)


def mlu_places(device_ids=None):
    return cuda_places(device_ids)


def cuda_pinned_places(device_count=None):
    from ..core.place import CUDAPinnedPlace

    return [CUDAPinnedPlace() for _ in range(device_count or 1)]


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """A persistable filled variable (reference: layers/tensor.py
    create_global_var)."""
    from .graph import create_parameter
    from ..nn import initializer as I

    return create_parameter(shape, dtype, name=name,
                            initializer=I.Constant(float(value)),
                            trainable=False)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print op (reference: layers/control_flow.py Print) via
    jax.debug.print so it fires inside compiled programs too."""
    from ..core.dispatch import apply
    from ..core.tensor import Tensor, to_tensor

    msg = message or ""

    def _fn(v):
        import jax

        jax.debug.print(msg + " {x}", x=v)
        return v

    return apply("print", _fn,
                 input if isinstance(input, Tensor) else to_tensor(input))


class WeightNormParamAttr:
    """ParamAttr requesting weight normalization (reference:
    python/paddle/static/__init__.py WeightNormParamAttr).  Consumed by
    nn.utils.weight_norm at layer-construction time."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


class IpuStrategy:  # pragma: no cover - non-TPU hardware shim
    def __init__(self):
        raise NotImplementedError("IPU is not a target of this framework")


class IpuCompiledProgram:  # pragma: no cover
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU is not a target of this framework")


def ipu_shard_guard(*a, **k):  # pragma: no cover
    raise NotImplementedError("IPU is not a target of this framework")
