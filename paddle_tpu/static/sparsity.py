"""paddle.static.sparsity (reference:
python/paddle/static/sparsity/__init__.py re-exporting
fluid.contrib.sparsity — ASP 2:4 structured pruning for static graphs).

One ASP engine for both modes: the mask math lives in
``paddle_tpu.incubate.asp`` (compute_mask_2_4 / check_sparsity); this
module adds the static-graph entry points and the excluded-layer
registry the reference keeps in its ASPHelper."""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..incubate import asp as _asp

__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers"]

# reference ASPHelper.__excluded_layers: per-Program (keyed by id; None =
# the implicit default program) name lists
_EXCLUDED: Dict[int, List[str]] = {}


def calculate_density(x) -> float:
    """Fraction of nonzeros (reference: fluid/contrib/sparsity/utils.py
    calculate_density)."""
    arr = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def set_excluded_layers(main_program=None, param_names=()):
    """Mark parameter names ASP must not prune (reference:
    sparsity/asp.py set_excluded_layers)."""
    _EXCLUDED.setdefault(id(main_program), [])
    _EXCLUDED[id(main_program)].extend(param_names)


def reset_excluded_layers(main_program=None):
    if main_program is None:
        _EXCLUDED.clear()
    else:
        _EXCLUDED.pop(id(main_program), None)


def _is_excluded(name, main_program=None) -> bool:
    names = _EXCLUDED.get(id(main_program), []) + _EXCLUDED.get(id(None), [])
    return any(name and name.startswith(n) for n in names if n)


def decorate(optimizer):
    """Wrap the optimizer so masks are re-applied after each step
    (reference: sparsity/asp.py decorate -> OptimizerWithSparsityGuarantee).
    Same wrapper as the dygraph path."""
    return _asp.decorate(optimizer)


def prune_model(model_or_program=None, main_program=None, n=2, m=4,
                mask_algo="mask_1d", with_mask=True):
    """Apply 2:4 masks to every prunable weight (reference:
    sparsity/asp.py prune_model).  Accepts a dygraph Layer (delegates to
    incubate.asp) or a static Program (prunes its parameters, honoring
    the excluded-layer registry)."""
    target = model_or_program if model_or_program is not None \
        else main_program
    if target is not None and hasattr(target, "named_parameters"):
        return _asp.prune_model(target, n=n, m=m, mask_algo=mask_algo,
                                with_mask=with_mask)
    # static Program path: prune its recorded parameters (create_parameter
    # records (param, init_fn) pairs on the program's startup actions)
    from . import graph as G

    prog = target or G.default_main_program()
    pruned = {}
    seen = set()
    params = []
    for entry in getattr(prog, "_startup_actions", []):
        p = entry[0]
        if id(p) not in seen:
            seen.add(id(p))
            params.append(p)
    for p in params:
        name = getattr(p, "name", "")
        arr = np.asarray(p._value)
        if arr.ndim != 2 or arr.shape[-1] % m or _is_excluded(name, prog):
            continue
        mask = _asp.compute_mask_2_4(arr)
        import jax.numpy as jnp

        p._value = jnp.asarray(arr * mask)
        if with_mask:
            p._asp_mask = mask
        pruned[name or f"param_{id(p)}"] = float(mask.mean())
    return pruned
