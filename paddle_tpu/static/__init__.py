"""paddle.static parity surface.

The reference's static graph mode (Program/Executor,
/root/reference/python/paddle/static) is subsumed by jit.to_static: a traced
function IS the program, XLA is the executor.  This module keeps the API
names that still make sense — InputSpec and inference-model save/load — and
raises clear errors for Program-construction APIs that have no TPU-native
equivalent.
"""
from __future__ import annotations

from ..jit import InputSpec, load as _jit_load, save as _jit_save  # noqa: F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    raise NotImplementedError(
        "Use paddle_tpu.jit.save(layer, path, input_spec=[...]) — the traced "
        "StableHLO artifact is the inference model")


def load_inference_model(path_prefix, executor=None, **kwargs):
    return _jit_load(path_prefix)


class Program:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "No static Program graph: compile functions with "
            "paddle_tpu.jit.to_static instead")


def default_main_program():
    raise NotImplementedError("no static graph mode; use jit.to_static")


def default_startup_program():
    raise NotImplementedError("no static graph mode; use jit.to_static")


def name_scope(name):
    import contextlib

    return contextlib.nullcontext()
