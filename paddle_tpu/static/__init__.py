"""paddle.static parity surface: true static-graph mode on XLA.

Reference: /root/reference/python/paddle/static (Program/Executor
re-exports, append_backward in fluid/backward.py, save/load_inference_model
in fluid/io.py, CompiledProgram).  Design notes in ./graph.py — a Program
records the same functional ops dygraph runs; Executor compiles the whole
program (forward+backward+optimizer) into one XLA executable.
"""
from __future__ import annotations

from ..jit import InputSpec  # noqa: F401
from . import nn  # noqa: F401
from .graph import (  # noqa: F401
    CompiledProgramWrapper as CompiledProgram,
    Executor,
    Program,
    Scope,
    Variable,
    append_backward,
    create_parameter,
    data,
    default_main_program,
    default_startup_program,
    disable_static,
    enable_static,
    global_scope,
    gradients,
    in_static_mode,
    load_inference_model,
    program_guard,
    save_inference_model,
    scope_guard,
)
from . import sparsity  # noqa: F401
from .passes import (  # noqa: F401
    apply_build_strategy, apply_pass, get_pass, list_passes, register_pass,
)
from . import passes  # noqa: F401
from .extras import (  # noqa: F401
    BuildStrategy, ExecutionStrategy, ExponentialMovingAverage,
    IpuCompiledProgram, IpuStrategy, ParallelExecutor, Print,
    WeightNormParamAttr, cpu_places, cuda_pinned_places, cuda_places,
    create_global_var, deserialize_persistables, deserialize_program,
    ipu_shard_guard, load_from_file, load_program_state, load_vars,
    mlu_places, normalize_program, npu_places, save_to_file, save_vars,
    serialize_persistables, serialize_program, set_program_state,
    xpu_places,
)
from ..ops.math import accuracy  # noqa: F401
from ..metric import Auc as _Auc

_auc_accumulators = {}


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """AUC with cross-batch accumulation (reference: static.auc over the
    auc op — returns (global_auc, batch_auc, states)).  The reference
    materializes the confusion-matrix state as program variables; here a
    per-config accumulator plays that role and is returned as `states`."""
    import numpy as np

    from ..core.tensor import to_tensor

    pred = np.asarray(input.numpy() if hasattr(input, "numpy") else input)
    lab = np.asarray(label.numpy() if hasattr(label, "numpy") else label)
    key = (curve, num_thresholds)
    acc = _auc_accumulators.get(key)
    if acc is None:
        acc = _auc_accumulators[key] = _Auc(curve=curve,
                                            num_thresholds=num_thresholds)
    acc.update(pred, lab)
    batch = _Auc(curve=curve, num_thresholds=num_thresholds)
    batch.update(pred, lab)
    return (to_tensor(np.asarray(acc.accumulate(), np.float32)),
            to_tensor(np.asarray(batch.accumulate(), np.float32)),
            [acc])


from .. import amp  # noqa: E402,F401  (paddle.static.amp parity alias)

py_func = None  # not supported: host callbacks break XLA compilation


def name_scope(name):
    import contextlib

    return contextlib.nullcontext()


def device_guard(device=None):
    import contextlib

    return contextlib.nullcontext()


from ..nn.layer.layers import ParamAttr  # noqa: F401,E402


def save(program, model_path, protocol=4, **configs):
    """static.save: persist all persistable parameters of a program."""
    import pickle

    import numpy as np

    state = {}
    for i, (t, _) in enumerate(program._startup_actions):
        state[getattr(t, "name", None) or f"param_{i}"] = np.asarray(t._value)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    import pickle

    import jax.numpy as jnp

    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    for i, (t, _) in enumerate(program._startup_actions):
        name = getattr(t, "name", None) or f"param_{i}"
        if name in state:
            t._value = jnp.asarray(state[name])
