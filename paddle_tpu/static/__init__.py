"""paddle.static parity surface: true static-graph mode on XLA.

Reference: /root/reference/python/paddle/static (Program/Executor
re-exports, append_backward in fluid/backward.py, save/load_inference_model
in fluid/io.py, CompiledProgram).  Design notes in ./graph.py — a Program
records the same functional ops dygraph runs; Executor compiles the whole
program (forward+backward+optimizer) into one XLA executable.
"""
from __future__ import annotations

from ..jit import InputSpec  # noqa: F401
from . import nn  # noqa: F401
from .graph import (  # noqa: F401
    CompiledProgramWrapper as CompiledProgram,
    Executor,
    Program,
    Scope,
    Variable,
    append_backward,
    create_parameter,
    data,
    default_main_program,
    default_startup_program,
    disable_static,
    enable_static,
    global_scope,
    gradients,
    in_static_mode,
    load_inference_model,
    program_guard,
    save_inference_model,
    scope_guard,
)
from .passes import (  # noqa: F401
    apply_build_strategy, apply_pass, get_pass, list_passes, register_pass,
)
from . import passes  # noqa: F401

py_func = None  # not supported: host callbacks break XLA compilation


def name_scope(name):
    import contextlib

    return contextlib.nullcontext()


def device_guard(device=None):
    import contextlib

    return contextlib.nullcontext()


from ..nn.layer.layers import ParamAttr  # noqa: F401,E402


def save(program, model_path, protocol=4, **configs):
    """static.save: persist all persistable parameters of a program."""
    import pickle

    import numpy as np

    state = {}
    for i, (t, _) in enumerate(program._startup_actions):
        state[getattr(t, "name", None) or f"param_{i}"] = np.asarray(t._value)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    import pickle

    import jax.numpy as jnp

    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    for i, (t, _) in enumerate(program._startup_actions):
        name = getattr(t, "name", None) or f"param_{i}"
        if name in state:
            t._value = jnp.asarray(state[name])
